// Native host-side ingest accelerator.
//
// The reference does its per-entry host work (base64, TLS-struct leaf
// decode, buffer shuffling) in compiled Go; the Python rebuild keeps
// parity lanes in Python but runs the BULK host path here: batched
// base64 decode, RFC 6962 MerkleTreeLeaf/extra_data decoding, and
// packing certificate bytes into the fixed-width [B, L] device layout
// (ct_mapreduce_tpu/core/packing.py schema). One call handles a whole
// get-entries batch with zero Python-object overhead; Python keeps the
// exact fallback (ct_mapreduce_tpu/ingest/leaf.py) for lanes this
// decoder flags.
//
// ABI: plain C, consumed via ctypes (no pybind11 in the image). All
// buffers are caller-allocated numpy arrays.

#include <cstdint>
#include <cstring>

namespace {

// RFC 4648 base64 (standard alphabet, '=' padding). Returns decoded
// length, or -1 on bad input. Whitespace is not tolerated — CT JSON
// carries clean base64.
struct B64Table {
  int8_t t[256];
  B64Table() {
    for (int i = 0; i < 256; ++i) t[i] = -1;
    const char* alpha =
        "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";
    for (int i = 0; i < 64; ++i) t[(uint8_t)alpha[i]] = (int8_t)i;
  }
};

int64_t b64_decode(const char* in, int64_t in_len, uint8_t* out) {
  // C++ magic static: thread-safe one-time init (multiple store
  // workers decode concurrently).
  static const B64Table table;
  // Match Python's b64decode(validate=True): total length must be a
  // multiple of 4 (padding included), at most 2 trailing '=' pads, and
  // any non-alphabet byte is fatal.
  if (in_len % 4 != 0) return -1;
  int pads = 0;
  while (in_len > 0 && in[in_len - 1] == '=') { --in_len; ++pads; }
  if (pads > 2) return -1;
  int64_t out_len = 0;
  uint32_t acc = 0;
  int bits = 0;
  for (int64_t i = 0; i < in_len; ++i) {
    int8_t v = table.t[(uint8_t)in[i]];
    if (v < 0) return -1;
    acc = (acc << 6) | (uint32_t)v;
    bits += 6;
    if (bits >= 8) {
      bits -= 8;
      out[out_len++] = (uint8_t)((acc >> bits) & 0xFF);
    }
  }
  return out_len;
}

struct Reader {
  const uint8_t* p;
  int64_t len;
  int64_t pos = 0;
  bool ok = true;

  uint64_t uint(int width) {
    if (pos + width > len) { ok = false; return 0; }
    uint64_t v = 0;
    for (int i = 0; i < width; ++i) v = (v << 8) | p[pos + i];
    pos += width;
    return v;
  }
  // TLS opaque<len_width>: returns (offset, length) into p.
  bool opaque(int len_width, int64_t* off, int64_t* olen) {
    uint64_t n = uint(len_width);
    if (!ok || pos + (int64_t)n > len) { ok = false; return false; }
    *off = pos;
    *olen = (int64_t)n;
    pos += (int64_t)n;
    return true;
  }
};

// FNV-1a 64-bit over a byte span (issuer-dedup hash).
uint64_t fnv1a(const uint8_t* p, int64_t n) {
  uint64_t h = 1469598103934665603ull;
  for (int64_t i = 0; i < n; ++i) {
    h ^= p[i];
    h *= 1099511628211ull;
  }
  return h;
}

}  // namespace

extern "C" {

// Status codes per entry (mirrors ingest/leaf.py error taxonomy).
enum {
  CTMR_OK = 0,
  CTMR_BAD_B64 = 1,
  CTMR_BAD_LEAF = 2,
  CTMR_UNSUPPORTED = 3,   // version/leaf_type/entry_type unknown
  CTMR_NO_CHAIN = 4,      // no issuer certificate in extra_data
  CTMR_TOO_LONG = 5,      // cert exceeds pad_len (a wider redecode
                          // can clear it; exact host lane otherwise)
  CTMR_ISSUER_TOO_LONG = 6,  // issuer DER >= 2 MiB: the cert itself
                          // packed fine, so a wider redecode is futile
                          // — straight to the exact host lane
};

// Decode one get-entries batch and pack leaf certificates.
//
// Inputs: n entries; leaf_input/extra_data base64 blobs concatenated in
// `li_buf`/`ed_buf` with offsets (n+1 entries, prefix-sum style).
// Outputs:
//   data      [n, pad_len] uint8  — packed certificate DER (zero-padded)
//   length    [n] int32           — true DER length (0 on error lanes)
//   ts_ms     [n] int64           — leaf timestamps
//   entry_ty  [n] int32           — 0 x509 / 1 precert
//   issuer_off/issuer_len [n] int64/int32 — issuer (chain[0]) DER span
//       inside scratch; issuer bytes are written to `issuer_buf`
//       sequentially; issuer_cap is its capacity.
//   status    [n] int32
// Returns bytes used in issuer_buf, or -1 if issuer_buf overflowed.
int64_t ctmr_decode_entries(
    int64_t n,
    const char* li_buf, const int64_t* li_off,
    const char* ed_buf, const int64_t* ed_off,
    int64_t pad_len,
    uint8_t* data, int32_t* length,
    int64_t* ts_ms, int32_t* entry_ty,
    uint8_t* issuer_buf, int64_t issuer_cap,
    int64_t* issuer_off, int32_t* issuer_len,
    int32_t* status,
    uint8_t* scratch, int64_t scratch_cap) {
  int64_t issuer_used = 0;
  // Issuer dedup: CT batches carry a handful of distinct issuers, so
  // identical chain[0] DERs share one span of issuer_buf (callers
  // group entries by (off, len) without re-hashing bytes in Python).
  // Fixed-size open-addressed table; on overflow we just append —
  // correctness never depends on a dedup hit.
  constexpr int kIssSlots = 512;  // power of two
  struct IssSlot { uint64_t h; int64_t off; int32_t len; };
  IssSlot iss_tab[kIssSlots];
  std::memset(iss_tab, 0, sizeof(iss_tab));
  for (int64_t i = 0; i < n; ++i) {
    status[i] = CTMR_OK;
    length[i] = 0;
    ts_ms[i] = 0;
    entry_ty[i] = 0;
    issuer_off[i] = 0;
    issuer_len[i] = 0;
    uint8_t* row = data + i * pad_len;
    std::memset(row, 0, (size_t)pad_len);

    // -- leaf_input ---------------------------------------------------
    const char* li = li_buf + li_off[i];
    int64_t li_n = li_off[i + 1] - li_off[i];
    if ((li_n * 3) / 4 + 4 > scratch_cap) { status[i] = CTMR_BAD_B64; continue; }
    int64_t li_dec = b64_decode(li, li_n, scratch);
    if (li_dec < 0) { status[i] = CTMR_BAD_B64; continue; }

    Reader r{scratch, li_dec};
    uint64_t version = r.uint(1);
    uint64_t leaf_type = r.uint(1);
    if (!r.ok || version != 0 || leaf_type != 0) {
      status[i] = r.ok ? CTMR_UNSUPPORTED : CTMR_BAD_LEAF;
      continue;
    }
    uint64_t ts = r.uint(8);
    uint64_t ety = r.uint(2);
    if (!r.ok) { status[i] = CTMR_BAD_LEAF; continue; }
    // ts_ms/entry_ty are stored only once every BAD_* path is behind
    // us (below, before the TOO_LONG check): the Python codec yields
    // them only when the whole decode succeeds, and the conformance
    // fuzz pins byte equality of every output array.

    int64_t cert_off = 0, cert_len = 0;
    if (ety == 0) {  // x509_entry: leaf cert in leaf_input
      if (!r.opaque(3, &cert_off, &cert_len)) { status[i] = CTMR_BAD_LEAF; continue; }
    } else if (ety == 1) {  // precert: issuer_key_hash + TBS (unused)
      r.pos += 32;
      int64_t toff, tlen;
      if (r.pos > r.len || !r.opaque(3, &toff, &tlen)) {
        status[i] = CTMR_BAD_LEAF; continue;
      }
    } else {
      status[i] = CTMR_UNSUPPORTED;
      continue;
    }
    // CtExtensions<2>: content ignored, but the frame must be intact —
    // leaf.py's r.opaque(2) raises on truncation, so parity demands the
    // same validation here.
    {
      int64_t xoff, xlen;
      if (!r.opaque(2, &xoff, &xlen)) { status[i] = CTMR_BAD_LEAF; continue; }
    }

    const uint8_t* cert_src = scratch + cert_off;

    // -- extra_data ---------------------------------------------------
    const char* ed = ed_buf + ed_off[i];
    int64_t ed_n = ed_off[i + 1] - ed_off[i];
    uint8_t* ed_scratch = scratch + (li_dec + 7) / 8 * 8;
    int64_t ed_cap = scratch_cap - (li_dec + 7) / 8 * 8;
    int64_t ed_dec = 0;
    if (ed_n > 0) {
      if ((ed_n * 3) / 4 + 4 > ed_cap) { status[i] = CTMR_BAD_B64; continue; }
      ed_dec = b64_decode(ed, ed_n, ed_scratch);
      if (ed_dec < 0) { status[i] = CTMR_BAD_B64; continue; }
    }

    Reader er{ed_scratch, ed_dec};
    if (ety == 1) {
      // PrecertChainEntry: pre_certificate<3> is what gets stored.
      int64_t poff, plen;
      if (!er.opaque(3, &poff, &plen)) { status[i] = CTMR_BAD_LEAF; continue; }
      cert_src = ed_scratch + poff;
      cert_len = plen;
    }
    // chain (both types): outer <3> frame of <3>-prefixed certs. The
    // whole frame must parse — the Python codec's _read_chain raises on
    // ANY truncated element (not just the first), so a malformed frame
    // is BAD_LEAF, never a silent "no chain".
    int64_t chain_issuer_off = -1, chain_issuer_len = 0;
    if (er.pos < er.len) {
      int64_t foff, flen;
      if (!er.opaque(3, &foff, &flen)) { status[i] = CTMR_BAD_LEAF; continue; }
      Reader cr{ed_scratch + foff, flen};
      bool chain_ok = true;
      bool first = true;
      while (cr.pos < cr.len) {
        int64_t coff, clen;
        if (!cr.opaque(3, &coff, &clen)) { chain_ok = false; break; }
        if (first) {
          chain_issuer_off = foff + coff;
          chain_issuer_len = clen;
          first = false;
        }
      }
      if (!chain_ok) { status[i] = CTMR_BAD_LEAF; continue; }
    }

    ts_ms[i] = (int64_t)ts;
    entry_ty[i] = (int32_t)ety;
    if (cert_len > pad_len) { status[i] = CTMR_TOO_LONG; continue; }
    std::memcpy(row, cert_src, (size_t)cert_len);
    length[i] = (int32_t)cert_len;

    if (chain_issuer_off < 0 || chain_issuer_len == 0) {
      status[i] = CTMR_NO_CHAIN;  // cert still packed; caller decides
      continue;
    }
    if (chain_issuer_len >= (1 << 21)) {
      // Pathological >=2 MiB issuer DER: the Python span packing
      // (off*2^21 + len) requires len < 2^21, so route the entry down
      // the exact per-entry host lane instead of risking aliasing.
      // Distinct from CTMR_TOO_LONG: the cert row IS packed, so the
      // caller must not trigger a full-width batch redecode for it.
      status[i] = CTMR_ISSUER_TOO_LONG;
      continue;
    }
    const uint8_t* iss_src = ed_scratch + chain_issuer_off;
    uint64_t h = fnv1a(iss_src, chain_issuer_len);
    if (h == 0) h = 1;  // 0 marks an empty slot
    int64_t found_off = -1;
    int probe = (int)(h & (kIssSlots - 1));
    int tries = 0;
    for (; tries < kIssSlots; ++tries) {
      IssSlot& s = iss_tab[probe];
      if (s.h == 0) break;  // miss — insert here after the append
      if (s.h == h && s.len == (int32_t)chain_issuer_len &&
          std::memcmp(issuer_buf + s.off, iss_src,
                      (size_t)chain_issuer_len) == 0) {
        found_off = s.off;
        break;
      }
      probe = (probe + 1) & (kIssSlots - 1);
    }
    if (found_off >= 0) {
      issuer_off[i] = found_off;
      issuer_len[i] = (int32_t)chain_issuer_len;
      continue;
    }
    if (issuer_used + chain_issuer_len > issuer_cap) return -1;
    std::memcpy(issuer_buf + issuer_used, iss_src,
                (size_t)chain_issuer_len);
    issuer_off[i] = issuer_used;
    issuer_len[i] = (int32_t)chain_issuer_len;
    if (tries < kIssSlots && iss_tab[probe].h == 0) {
      iss_tab[probe] = {h, issuer_used, (int32_t)chain_issuer_len};
    }
    issuer_used += chain_issuer_len;
  }
  return issuer_used;
}

// Pack pre-decoded DER blobs (concatenated in `blob` with prefix-sum
// offsets) into the [n, pad_len] device layout. Returns count packed;
// lanes whose cert exceeds pad_len get length 0 and ok[i] = 0.
int64_t ctmr_pack_ders(
    int64_t n,
    const uint8_t* blob, const int64_t* off,
    int64_t pad_len,
    uint8_t* data, int32_t* length, uint8_t* okflags) {
  int64_t packed = 0;
  for (int64_t i = 0; i < n; ++i) {
    uint8_t* row = data + i * pad_len;
    std::memset(row, 0, (size_t)pad_len);
    int64_t len = off[i + 1] - off[i];
    if (len > pad_len) { length[i] = 0; okflags[i] = 0; continue; }
    std::memcpy(row, blob + off[i], (size_t)len);
    length[i] = (int32_t)len;
    okflags[i] = 1;
    ++packed;
  }
  return packed;
}

}  // extern "C"
