// Native host-side ingest accelerator.
//
// The reference does its per-entry host work (base64, TLS-struct leaf
// decode, buffer shuffling) in compiled Go; the Python rebuild keeps
// parity lanes in Python but runs the BULK host path here: batched
// base64 decode, RFC 6962 MerkleTreeLeaf/extra_data decoding, and
// packing certificate bytes into the fixed-width [B, L] device layout
// (ct_mapreduce_tpu/core/packing.py schema). One call handles a whole
// get-entries batch with zero Python-object overhead; Python keeps the
// exact fallback (ct_mapreduce_tpu/ingest/leaf.py) for lanes this
// decoder flags.
//
// ABI: plain C, consumed via ctypes (no pybind11 in the image). All
// buffers are caller-allocated numpy arrays.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

namespace pool {

// Persistent, lazily-grown worker pool shared by the *_mt entry
// points. One global instance per process; threads are created the
// first time a caller asks for them and then parked on a condition
// variable between batches (thread create/join per 64K-entry chunk
// would cost more than the decode it parallelizes). The instance is
// deliberately leaked: parked workers may still exist at process
// exit and C++ static destruction order makes tearing them down
// unsafe — the OS reclaims them.
class WorkerPool {
 public:
  static WorkerPool& get() {
    static WorkerPool* p = new WorkerPool();
    return *p;
  }

  int active_workers() {
    std::lock_guard<std::mutex> lk(mu_);
    return (int)workers_.size() + 1;  // + the calling thread
  }

  // Run fn(chunk) for chunk in [0, n_chunks) with up to `threads`
  // concurrent executors (the calling thread participates). Blocks
  // until every chunk finished. Chunk claiming is an atomic counter,
  // so which THREAD runs a chunk is nondeterministic — callers must
  // make each chunk's writes a pure function of its chunk id (disjoint
  // output ranges, no shared accumulators) to keep results
  // bit-identical to a serial pass.
  void run(int threads, int n_chunks, const std::function<void(int)>& fn) {
    if (threads <= 1 || n_chunks <= 1) {
      for (int c = 0; c < n_chunks; ++c) fn(c);
      return;
    }
    // One parallel region at a time: the Python side may issue
    // concurrent decode calls (overlap pipeline workers); the second
    // caller just runs serially rather than queueing behind the pool.
    std::unique_lock<std::mutex> region(run_mu_, std::try_to_lock);
    if (!region.owns_lock()) {
      for (int c = 0; c < n_chunks; ++c) fn(c);
      return;
    }
    {
      std::lock_guard<std::mutex> lk(mu_);
      while ((int)workers_.size() < threads - 1) {
        workers_.emplace_back([this] { worker_loop(); });
      }
      fn_ = fn;
      remaining_ = n_chunks;
      // n_chunks_ and fn_ are published by the release store on
      // next_: a worker only dereferences them after its acquire
      // fetch_add observes the reset counter.
      n_chunks_.store(n_chunks, std::memory_order_relaxed);
      next_.store(0, std::memory_order_release);
      ++epoch_;
    }
    cv_.notify_all();
    work();  // caller participates
    std::unique_lock<std::mutex> lk(mu_);
    done_cv_.wait(lk, [&] { return remaining_ == 0; });
  }

 private:
  void work() {
    for (;;) {
      int c = next_.fetch_add(1, std::memory_order_acquire);
      if (c >= n_chunks_.load(std::memory_order_relaxed)) return;
      fn_(c);
      std::lock_guard<std::mutex> lk(mu_);
      if (--remaining_ == 0) done_cv_.notify_all();
    }
  }
  void worker_loop() {
    uint64_t seen = 0;
    for (;;) {
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [&] { return epoch_ != seen; });
        seen = epoch_;
      }
      work();
    }
  }

  std::mutex run_mu_;  // serializes parallel regions
  std::mutex mu_;      // guards pool state below
  std::condition_variable cv_, done_cv_;
  std::vector<std::thread> workers_;
  std::function<void(int)> fn_;
  std::atomic<int> next_{0};
  std::atomic<int> n_chunks_{0};
  int remaining_ = 0;
  uint64_t epoch_ = 0;
};

}  // namespace pool

namespace {

// RFC 4648 base64 (standard alphabet, '=' padding). Returns decoded
// length, or -1 on bad input. Whitespace is not tolerated — CT JSON
// carries clean base64.
struct B64Table {
  int8_t t[256];
  B64Table() {
    for (int i = 0; i < 256; ++i) t[i] = -1;
    const char* alpha =
        "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";
    for (int i = 0; i < 64; ++i) t[(uint8_t)alpha[i]] = (int8_t)i;
  }
};

int64_t b64_decode(const char* in, int64_t in_len, uint8_t* out) {
  // C++ magic static: thread-safe one-time init (multiple store
  // workers decode concurrently).
  static const B64Table table;
  // Match Python's b64decode(validate=True): total length must be a
  // multiple of 4 (padding included), at most 2 trailing '=' pads, and
  // any non-alphabet byte is fatal.
  if (in_len % 4 != 0) return -1;
  int pads = 0;
  while (in_len > 0 && in[in_len - 1] == '=') { --in_len; ++pads; }
  if (pads > 2) return -1;
  int64_t out_len = 0;
  uint32_t acc = 0;
  int bits = 0;
  for (int64_t i = 0; i < in_len; ++i) {
    int8_t v = table.t[(uint8_t)in[i]];
    if (v < 0) return -1;
    acc = (acc << 6) | (uint32_t)v;
    bits += 6;
    if (bits >= 8) {
      bits -= 8;
      out[out_len++] = (uint8_t)((acc >> bits) & 0xFF);
    }
  }
  return out_len;
}

struct Reader {
  const uint8_t* p;
  int64_t len;
  int64_t pos = 0;
  bool ok = true;

  uint64_t uint(int width) {
    if (pos + width > len) { ok = false; return 0; }
    uint64_t v = 0;
    for (int i = 0; i < width; ++i) v = (v << 8) | p[pos + i];
    pos += width;
    return v;
  }
  // TLS opaque<len_width>: returns (offset, length) into p.
  bool opaque(int len_width, int64_t* off, int64_t* olen) {
    uint64_t n = uint(len_width);
    if (!ok || pos + (int64_t)n > len) { ok = false; return false; }
    *off = pos;
    *olen = (int64_t)n;
    pos += (int64_t)n;
    return true;
  }
};

// FNV-1a 64-bit over a byte span (issuer-dedup hash).
uint64_t fnv1a(const uint8_t* p, int64_t n) {
  uint64_t h = 1469598103934665603ull;
  for (int64_t i = 0; i < n; ++i) {
    h ^= p[i];
    h *= 1099511628211ull;
  }
  return h;
}

}  // namespace

extern "C" {

// Status codes per entry (mirrors ingest/leaf.py error taxonomy).
enum {
  CTMR_OK = 0,
  CTMR_BAD_B64 = 1,
  CTMR_BAD_LEAF = 2,
  CTMR_UNSUPPORTED = 3,   // version/leaf_type/entry_type unknown
  CTMR_NO_CHAIN = 4,      // no issuer certificate in extra_data
  CTMR_TOO_LONG = 5,      // cert exceeds pad_len (a wider redecode
                          // can clear it; exact host lane otherwise)
  CTMR_ISSUER_TOO_LONG = 6,  // issuer DER >= 2 MiB: the cert itself
                          // packed fine, so a wider redecode is futile
                          // — straight to the exact host lane
};

// Decode one get-entries batch and pack leaf certificates.
//
// Inputs: n entries; leaf_input/extra_data base64 blobs concatenated in
// `li_buf`/`ed_buf` with offsets (n+1 entries, prefix-sum style).
// Outputs:
//   data      [n, pad_len] uint8  — packed certificate DER (zero-padded)
//   length    [n] int32           — true DER length (0 on error lanes)
//   ts_ms     [n] int64           — leaf timestamps
//   entry_ty  [n] int32           — 0 x509 / 1 precert
//   issuer_off/issuer_len [n] int64/int32 — issuer (chain[0]) DER span
//       inside scratch; issuer bytes are written to `issuer_buf`
//       sequentially; issuer_cap is its capacity.
//   status    [n] int32
// Returns bytes used in issuer_buf, or -1 if issuer_buf overflowed.
int64_t ctmr_decode_entries(
    int64_t n,
    const char* li_buf, const int64_t* li_off,
    const char* ed_buf, const int64_t* ed_off,
    int64_t pad_len,
    uint8_t* data, int32_t* length,
    int64_t* ts_ms, int32_t* entry_ty,
    uint8_t* issuer_buf, int64_t issuer_cap,
    int64_t* issuer_off, int32_t* issuer_len,
    int32_t* status,
    uint8_t* scratch, int64_t scratch_cap) {
  int64_t issuer_used = 0;
  // Issuer dedup: CT batches carry a handful of distinct issuers, so
  // identical chain[0] DERs share one span of issuer_buf (callers
  // group entries by (off, len) without re-hashing bytes in Python).
  // Fixed-size open-addressed table; on overflow we just append —
  // correctness never depends on a dedup hit.
  constexpr int kIssSlots = 512;  // power of two
  struct IssSlot { uint64_t h; int64_t off; int32_t len; };
  IssSlot iss_tab[kIssSlots];
  std::memset(iss_tab, 0, sizeof(iss_tab));
  for (int64_t i = 0; i < n; ++i) {
    status[i] = CTMR_OK;
    length[i] = 0;
    ts_ms[i] = 0;
    entry_ty[i] = 0;
    issuer_off[i] = 0;
    issuer_len[i] = 0;
    uint8_t* row = data + i * pad_len;
    std::memset(row, 0, (size_t)pad_len);

    // -- leaf_input ---------------------------------------------------
    const char* li = li_buf + li_off[i];
    int64_t li_n = li_off[i + 1] - li_off[i];
    if ((li_n * 3) / 4 + 4 > scratch_cap) { status[i] = CTMR_BAD_B64; continue; }
    int64_t li_dec = b64_decode(li, li_n, scratch);
    if (li_dec < 0) { status[i] = CTMR_BAD_B64; continue; }

    Reader r{scratch, li_dec};
    uint64_t version = r.uint(1);
    uint64_t leaf_type = r.uint(1);
    if (!r.ok || version != 0 || leaf_type != 0) {
      status[i] = r.ok ? CTMR_UNSUPPORTED : CTMR_BAD_LEAF;
      continue;
    }
    uint64_t ts = r.uint(8);
    uint64_t ety = r.uint(2);
    if (!r.ok) { status[i] = CTMR_BAD_LEAF; continue; }
    // ts_ms/entry_ty are stored only once every BAD_* path is behind
    // us (below, before the TOO_LONG check): the Python codec yields
    // them only when the whole decode succeeds, and the conformance
    // fuzz pins byte equality of every output array.

    int64_t cert_off = 0, cert_len = 0;
    if (ety == 0) {  // x509_entry: leaf cert in leaf_input
      if (!r.opaque(3, &cert_off, &cert_len)) { status[i] = CTMR_BAD_LEAF; continue; }
    } else if (ety == 1) {  // precert: issuer_key_hash + TBS (unused)
      r.pos += 32;
      int64_t toff, tlen;
      if (r.pos > r.len || !r.opaque(3, &toff, &tlen)) {
        status[i] = CTMR_BAD_LEAF; continue;
      }
    } else {
      status[i] = CTMR_UNSUPPORTED;
      continue;
    }
    // CtExtensions<2>: content ignored, but the frame must be intact —
    // leaf.py's r.opaque(2) raises on truncation, so parity demands the
    // same validation here.
    {
      int64_t xoff, xlen;
      if (!r.opaque(2, &xoff, &xlen)) { status[i] = CTMR_BAD_LEAF; continue; }
    }

    const uint8_t* cert_src = scratch + cert_off;

    // -- extra_data ---------------------------------------------------
    const char* ed = ed_buf + ed_off[i];
    int64_t ed_n = ed_off[i + 1] - ed_off[i];
    uint8_t* ed_scratch = scratch + (li_dec + 7) / 8 * 8;
    int64_t ed_cap = scratch_cap - (li_dec + 7) / 8 * 8;
    int64_t ed_dec = 0;
    if (ed_n > 0) {
      if ((ed_n * 3) / 4 + 4 > ed_cap) { status[i] = CTMR_BAD_B64; continue; }
      ed_dec = b64_decode(ed, ed_n, ed_scratch);
      if (ed_dec < 0) { status[i] = CTMR_BAD_B64; continue; }
    }

    Reader er{ed_scratch, ed_dec};
    if (ety == 1) {
      // PrecertChainEntry: pre_certificate<3> is what gets stored.
      int64_t poff, plen;
      if (!er.opaque(3, &poff, &plen)) { status[i] = CTMR_BAD_LEAF; continue; }
      cert_src = ed_scratch + poff;
      cert_len = plen;
    }
    // chain (both types): outer <3> frame of <3>-prefixed certs. The
    // whole frame must parse — the Python codec's _read_chain raises on
    // ANY truncated element (not just the first), so a malformed frame
    // is BAD_LEAF, never a silent "no chain".
    int64_t chain_issuer_off = -1, chain_issuer_len = 0;
    if (er.pos < er.len) {
      int64_t foff, flen;
      if (!er.opaque(3, &foff, &flen)) { status[i] = CTMR_BAD_LEAF; continue; }
      Reader cr{ed_scratch + foff, flen};
      bool chain_ok = true;
      bool first = true;
      while (cr.pos < cr.len) {
        int64_t coff, clen;
        if (!cr.opaque(3, &coff, &clen)) { chain_ok = false; break; }
        if (first) {
          chain_issuer_off = foff + coff;
          chain_issuer_len = clen;
          first = false;
        }
      }
      if (!chain_ok) { status[i] = CTMR_BAD_LEAF; continue; }
    }

    ts_ms[i] = (int64_t)ts;
    entry_ty[i] = (int32_t)ety;
    if (cert_len > pad_len) { status[i] = CTMR_TOO_LONG; continue; }
    std::memcpy(row, cert_src, (size_t)cert_len);
    length[i] = (int32_t)cert_len;

    if (chain_issuer_off < 0 || chain_issuer_len == 0) {
      status[i] = CTMR_NO_CHAIN;  // cert still packed; caller decides
      continue;
    }
    if (chain_issuer_len >= (1 << 21)) {
      // Pathological >=2 MiB issuer DER: the Python span packing
      // (off*2^21 + len) requires len < 2^21, so route the entry down
      // the exact per-entry host lane instead of risking aliasing.
      // Distinct from CTMR_TOO_LONG: the cert row IS packed, so the
      // caller must not trigger a full-width batch redecode for it.
      status[i] = CTMR_ISSUER_TOO_LONG;
      continue;
    }
    const uint8_t* iss_src = ed_scratch + chain_issuer_off;
    uint64_t h = fnv1a(iss_src, chain_issuer_len);
    if (h == 0) h = 1;  // 0 marks an empty slot
    int64_t found_off = -1;
    int probe = (int)(h & (kIssSlots - 1));
    int tries = 0;
    for (; tries < kIssSlots; ++tries) {
      IssSlot& s = iss_tab[probe];
      if (s.h == 0) break;  // miss — insert here after the append
      if (s.h == h && s.len == (int32_t)chain_issuer_len &&
          std::memcmp(issuer_buf + s.off, iss_src,
                      (size_t)chain_issuer_len) == 0) {
        found_off = s.off;
        break;
      }
      probe = (probe + 1) & (kIssSlots - 1);
    }
    if (found_off >= 0) {
      issuer_off[i] = found_off;
      issuer_len[i] = (int32_t)chain_issuer_len;
      continue;
    }
    if (issuer_used + chain_issuer_len > issuer_cap) return -1;
    std::memcpy(issuer_buf + issuer_used, iss_src,
                (size_t)chain_issuer_len);
    issuer_off[i] = issuer_used;
    issuer_len[i] = (int32_t)chain_issuer_len;
    if (tries < kIssSlots && iss_tab[probe].h == 0) {
      iss_tab[probe] = {h, issuer_used, (int32_t)chain_issuer_len};
    }
    issuer_used += chain_issuer_len;
  }
  return issuer_used;
}

// ---------------------------------------------------------------------
// Pre-parsed ingest sidecars: a SCALAR PORT of the device DER walker
// (ct_mapreduce_tpu/ops/der_kernel.py parse_certs_rows).
//
// The contract is bit-exactness with the device walker on EVERY input,
// not "a good X.509 parser": the pre-parsed ingest lane substitutes
// these host-extracted fields for the on-device walk, and any
// divergence (a lane one side accepts and the other rejects, or a
// field extracted differently) silently re-routes entries between the
// device dedup domain and the exact host lane — the ParsEval failure
// mode (arXiv:2405.18993). So every quirk of the walker is reproduced
// deliberately: fixed byte-window limits around each merged header
// group (reads outside a window see zeros), long-form lengths capped
// at 3 octets, the MAX_RDNS/MAX_EXTS scan budgets, first-ATV-per-RDN /
// first-CN-wins CN selection, day<=31 non-calendar time validation,
// and the extnValue-overrun lane rejection. tests/test_preparsed.py
// pins `extract == parse_certs` across the mutation fuzz.

namespace walker {

constexpr int kMaxRdns = 12;   // der_kernel.MAX_RDNS
constexpr int kMaxExts = 24;   // der_kernel.MAX_EXTS

// One certificate row in the padded [pad_len] layout (zero padding
// beyond `length` is guaranteed by the packers above).
struct Row {
  const uint8_t* p;
  int64_t pad_len;
  int64_t nwb;  // padded word bytes = ceil(pad_len/4)*4 (zeros past pad)

  // Byte `rel` of the W-byte window anchored at position `pos`
  // (der_kernel._window + _wbyte): window byte j is row byte
  // clip(pos)&~3 + j; out-of-window reads are zero, matching the
  // one-hot select's masked sum.
  int wbyte(int64_t pos, int64_t rel, int W) const {
    if (rel < 0 || rel >= W) return 0;
    int64_t base = pos < 0 ? 0 : pos;
    int64_t cap = (nwb / 4 - 1) * 4;
    if (base > cap) base = cap;
    base &= ~int64_t{3};
    int64_t q = base + rel;
    return (q >= 0 && q < pad_len) ? p[q] : 0;
  }
};

struct Hdr {
  int64_t tag = 0, clen = 0, hlen = 0;
  bool ok = false;
};

// _read_header_w: TLV header at row position pos+delta read through
// the W-byte window anchored at `pos`. Short form or long form up to
// 3 length octets; ok requires the whole frame inside `limit`.
inline Hdr read_header(const Row& r, int64_t pos, int64_t delta,
                       int64_t limit, int W) {
  int64_t a = (pos < 0 ? 0 : pos) & 3;
  int64_t rel = a + delta;
  Hdr h;
  h.tag = r.wbyte(pos, rel, W);
  int64_t b0 = r.wbyte(pos, rel + 1, W);
  int64_t b1 = r.wbyte(pos, rel + 2, W);
  int64_t b2 = r.wbyte(pos, rel + 3, W);
  int64_t b3 = r.wbyte(pos, rel + 4, W);
  bool short_form = b0 < 0x80;
  int64_t n_len = b0 - 0x80;
  bool long_ok = (b0 > 0x80) && (n_len <= 3);
  int64_t clen_long = n_len == 1 ? b1
                      : n_len == 2 ? ((b1 << 8) | b2)
                                   : ((b1 << 16) | (b2 << 8) | b3);
  h.clen = short_form ? b0 : clen_long;
  h.hlen = short_form ? 2 : 2 + n_len;
  int64_t at = pos + delta;
  h.ok = (short_form || long_ok) && at >= 0 && at + h.hlen + h.clen <= limit;
  return h;
}

// _parse_time_w: UTCTime/GeneralizedTime at pos+delta (window at pos).
// Mirrors the walker exactly: strict ASCII-digit checks on every byte
// feeding the bucket, month 1-12 / day 1-31 / hour 0-23 ranges, NO
// calendar (leap/length-of-month) or minutes/seconds validation.
inline bool parse_time(const Row& r, int64_t pos, int64_t delta, int W,
                       int32_t* hour_out) {
  Hdr h = read_header(r, pos, delta, int64_t{1} << 30, W);
  bool is_utc = h.tag == 0x17;
  bool is_gen = h.tag == 0x18;
  if (!h.ok || !(is_utc || is_gen)) return false;
  if (is_utc ? h.clen < 11 : h.clen < 13) return false;
  int64_t a = (pos < 0 ? 0 : pos) & 3;
  int64_t q = a + delta + h.hlen;
  auto d2 = [&](int64_t off, int64_t* out) -> bool {
    int b0 = r.wbyte(pos, off, W), b1 = r.wbyte(pos, off + 1, W);
    if (b0 < 0x30 || b0 > 0x39 || b1 < 0x30 || b1 > 0x39) return false;
    *out = (b0 - 0x30) * 10 + (b1 - 0x30);
    return true;
  };
  int64_t yy, cc = 0, month, day, hour;
  if (!d2(q, &yy)) return false;
  int64_t year;
  if (is_utc) {
    year = yy >= 50 ? 1900 + yy : 2000 + yy;
  } else {
    if (!d2(q + 2, &cc)) return false;
    year = yy * 100 + cc;
  }
  int64_t body = is_utc ? q : q + 2;
  if (!d2(body + 2, &month) || !d2(body + 4, &day) || !d2(body + 6, &hour))
    return false;
  if (month < 1 || month > 12 || day < 1 || day > 31 || hour > 23)
    return false;
  // Days-from-civil (identical arithmetic; floor divisions — all the
  // operands are non-negative here except the final epoch shift).
  int64_t y = year - (month <= 2 ? 1 : 0);
  int64_t era = y / 400;  // year >= 1900-ish in practice; y >= 0 always
  int64_t yoe = y - era * 400;
  int64_t mp = month > 2 ? month - 3 : month + 9;
  int64_t doy = (153 * mp + 2) / 5 + day - 1;
  int64_t doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
  int64_t days = era * 146097 + doe - 719468;
  *hour_out = (int32_t)(days * 24 + hour);
  return true;
}

struct Sidecar {
  uint8_t ok = 0;
  int32_t serial_off = 0, serial_len = 0;
  int32_t not_after_hour = 0;
  uint8_t is_ca = 0, has_crldp = 0;
  int32_t cn_off = 0, cn_len = 0;
  int32_t issuer_off = 0, issuer_len = 0;
  int32_t spki_off = 0, spki_len = 0;
  int32_t crldp_off = 0, crldp_len = 0;
};

// _scan_issuer_cn: first CN (OID 2.5.4.3) via first-ATV-per-RDN-SET
// rounds in an 8-word (32B) window per round; structural breaks stop
// the scan silently (never affect the lane's ok).
inline void scan_issuer_cn(const Row& r, int64_t off, int64_t end,
                           bool alive0, Sidecar* s) {
  constexpr int W = 32;
  int64_t p = off, cn_off = 0, cn_len = 0;
  int cnt = 0;
  bool alive = alive0;
  while (alive && p < end && cnt < kMaxRdns) {
    int64_t a = (p < 0 ? 0 : p) & 3;
    Hdr set = read_header(r, p, 0, end, W);
    bool set_ok = set.ok && set.tag == 0x31;
    int64_t da = set.hlen;
    Hdr atv = read_header(r, p, da, end, W);
    int64_t dro = da + atv.hlen;
    Hdr oid = read_header(r, p, dro, end, W);
    int64_t ro = a + dro + oid.hlen;
    bool is_cn = set_ok && atv.ok && atv.tag == 0x30 && oid.ok
        && oid.tag == 0x06 && oid.clen == 3
        && r.wbyte(p, ro, W) == 0x55 && r.wbyte(p, ro + 1, W) == 0x04
        && r.wbyte(p, ro + 2, W) == 0x03;
    int64_t dv = dro + oid.hlen + oid.clen;
    Hdr val = read_header(r, p, dv, end, W);
    if (is_cn && val.ok && cn_len == 0) {
      cn_off = p + dv + val.hlen;
      cn_len = val.clen;
    }
    if (set.ok) {
      p += set.hlen + set.clen;
      ++cnt;
    }
    alive = alive && set.ok;
  }
  s->cn_off = (int32_t)cn_off;
  s->cn_len = (int32_t)cn_len;
}

// _scan_extensions + _ext_round: BasicConstraints CA + CRLDP windows,
// 11-word (44B) window per round, per-lane budget kMaxExts; a header
// failure or extnValue overrun rejects the lane, exhausting the
// budget mid-list rejects it too. Returns the lane's ext_ok.
inline bool scan_extensions(const Row& r, int64_t off, int64_t end,
                            bool alive0, Sidecar* s) {
  constexpr int W = 44;
  int64_t p = off;
  int cnt = 0;
  bool alive = alive0;
  bool live = alive0 && p < end;
  while (live) {
    int64_t a = (p < 0 ? 0 : p) & 3;
    Hdr e = read_header(r, p, 0, end, W);
    bool ext_ok = e.ok && e.tag == 0x30;
    int64_t di = e.hlen;
    Hdr oid = read_header(r, p, di, end, W);
    bool oid_ok = ext_ok && oid.ok && oid.tag == 0x06 && oid.clen == 3;
    int64_t ro = a + di + oid.hlen;
    int o0 = r.wbyte(p, ro, W), o1 = r.wbyte(p, ro + 1, W),
        o2 = r.wbyte(p, ro + 2, W);
    bool is_bc = oid_ok && o0 == 0x55 && o1 == 0x1D && o2 == 0x13;
    bool is_dp = oid_ok && o0 == 0x55 && o1 == 0x1D && o2 == 0x1F;
    int64_t dc = di + oid.hlen + oid.clen;
    Hdr crit = read_header(r, p, dc, end, W);
    bool has_crit = crit.ok && crit.tag == 0x01;
    int64_t dv = has_crit ? dc + crit.hlen + crit.clen : dc;
    Hdr val = read_header(r, p, dv, end, W);
    Hdr val2 = read_header(r, p, dv, int64_t{1} << 30, W);
    bool overrun = ext_ok && val2.ok
        && dv + val2.hlen + val2.clen > e.hlen + e.clen;
    bool val_ok = val.ok && val.tag == 0x04 && !overrun;
    int64_t db = dv + val.hlen;
    Hdr bc = read_header(r, p, db, end, W);
    bool bc_seq_ok = val_ok && bc.ok && bc.tag == 0x30;
    int64_t df = db + bc.hlen;
    Hdr f = read_header(r, p, df, end, W);
    bool ca_flag = bc_seq_ok && bc.clen > 0 && f.ok && f.tag == 0x01
        && f.clen == 1 && r.wbyte(p, a + df + f.hlen, W) != 0;
    if (is_bc && ca_flag) s->is_ca = 1;
    if (is_dp && val_ok && s->crldp_len == 0) {
      s->crldp_off = (int32_t)(p + dv + val.hlen);
      s->crldp_len = (int32_t)val.clen;
    }
    if (is_dp && val_ok) s->has_crldp = 1;
    if (e.ok) {
      p += e.hlen + e.clen;
      ++cnt;
    }
    alive = alive && e.ok && !overrun;
    live = alive && p < end && cnt < kMaxExts;
  }
  bool exhausted = alive && p < end;  // budget ran out mid-list
  return alive && !exhausted;
}

// parse_certs_rows, one lane: the fixed straight-line walk with the
// same merged windows (w1 17 words anchored at 0; per-header windows
// for the issuer/SPKI headers; w3/w4 13 words) and in-window guards.
inline Sidecar extract_one(const uint8_t* row, int64_t pad_len,
                           int64_t length) {
  Sidecar s;
  Row r{row, pad_len, (pad_len + 3) / 4 * 4};
  int64_t limit = length;
  bool ok = length > 4;

  constexpr int W1 = 68;  // 17 words
  Hdr h = read_header(r, 0, 0, limit, W1);
  ok = ok && h.ok && h.tag == 0x30;
  int64_t d_tbs = h.hlen;
  h = read_header(r, 0, d_tbs, limit, W1);
  ok = ok && h.ok && h.tag == 0x30;
  int64_t tbs_end = d_tbs + h.hlen + h.clen;
  int64_t d = d_tbs + h.hlen;
  Hdr v = read_header(r, 0, d, tbs_end, W1);
  int64_t dser = d + (v.ok && v.tag == 0xA0 ? v.hlen + v.clen : 0);
  h = read_header(r, 0, dser, tbs_end, W1);
  ok = ok && h.ok && h.tag == 0x02 && dser + 5 <= W1;  // a == 0 at pos 0
  int64_t serial_off = dser + h.hlen;
  int64_t serial_len = h.clen;
  int64_t d_alg = dser + h.hlen + h.clen;
  h = read_header(r, 0, d_alg, tbs_end, W1);
  ok = ok && h.ok && h.tag == 0x30 && d_alg + 5 <= W1;
  int64_t p = d_alg + h.hlen + h.clen;

  // issuer Name header (own window, like _header_at's 3 words)
  h = read_header(r, p, 0, tbs_end, 12);
  ok = ok && h.ok && h.tag == 0x30;
  int64_t issuer_off = p;
  int64_t issuer_len = h.hlen + h.clen;
  scan_issuer_cn(r, p + h.hlen, p + h.hlen + h.clen, ok, &s);
  p += h.hlen + h.clen;

  constexpr int W3 = 52;  // 13 words
  h = read_header(r, p, 0, tbs_end, W3);
  ok = ok && h.ok && h.tag == 0x30;
  int64_t dnb = h.hlen;
  Hdr nb = read_header(r, p, dnb, tbs_end, W3);
  ok = ok && nb.ok;
  int32_t nah = 0;
  ok = parse_time(r, p, dnb + nb.hlen + nb.clen, W3, &nah) && ok;
  int64_t d_subj = h.hlen + h.clen;
  Hdr subj = read_header(r, p, d_subj, tbs_end, W3);
  ok = ok && subj.ok && subj.tag == 0x30
      && ((p < 0 ? 0 : p) & 3) + d_subj + 5 <= W3;
  p += d_subj + subj.hlen + subj.clen;

  // SPKI header (own window)
  h = read_header(r, p, 0, tbs_end, 12);
  ok = ok && h.ok && h.tag == 0x30;
  int64_t spki_off = p;
  int64_t spki_len = h.hlen + h.clen;
  p += h.hlen + h.clen;

  constexpr int W4 = 52;
  int64_t a4 = (p < 0 ? 0 : p) & 3;
  d = 0;
  for (int round = 0; round < 2; ++round) {
    Hdr u = read_header(r, p, d, tbs_end, W4);
    bool is_uid = u.ok && (u.tag == 0x81 || u.tag == 0x82 || u.tag == 0xA1
                           || u.tag == 0xA2);
    if (is_uid) d += u.hlen + u.clen;
  }
  bool in_win = a4 + d + 11 <= W4;
  Hdr x = read_header(r, p, d, tbs_end, W4);
  bool has_ext = x.ok && x.tag == 0xA3 && p + d < tbs_end && in_win;
  // Undecodable trailing TBS bytes → exact host lane (see the
  // matching guard in der_kernel.parse_certs_rows).
  ok = ok && (has_ext || p + d >= tbs_end);
  int64_t de = d + x.hlen;
  Hdr el = read_header(r, p, de, tbs_end, W4);
  bool ext_listed = has_ext && el.ok && el.tag == 0x30;
  if (has_ext) ok = ok && el.ok && el.tag == 0x30;
  int64_t ext_off = p + de + el.hlen;
  int64_t ext_end = ext_listed ? p + de + el.hlen + el.clen : 0;
  ok = scan_extensions(r, ext_off, ext_end, ok, &s) && ok;

  s.ok = ok ? 1 : 0;
  if (ok) {
    s.serial_off = (int32_t)serial_off;
    s.serial_len = (int32_t)serial_len;
    s.not_after_hour = nah;
    s.issuer_off = (int32_t)issuer_off;
    s.issuer_len = (int32_t)issuer_len;
    s.spki_off = (int32_t)spki_off;
    s.spki_len = (int32_t)spki_len;
  } else {
    // Lane goes back through the device walker (or the exact host
    // lane) — zero every field like parse_certs_rows' jnp.where(ok, .)
    // masking, so callers can't consume half-extracted values.
    s = Sidecar{};
  }
  return s;
}

}  // namespace walker

extern "C" {

// Per-entry pre-parsed identity sidecars for a packed [n, pad_len]
// batch (the rows ctmr_decode_entries/ctmr_pack_ders produce). Lanes
// with length[i] == 0 come back ok=0. All output arrays length n.
void ctmr_extract_sidecars(
    int64_t n,
    const uint8_t* data, int64_t pad_len, const int32_t* length,
    uint8_t* ok,
    int32_t* serial_off, int32_t* serial_len,
    int32_t* not_after_hour,
    uint8_t* is_ca, uint8_t* has_crldp,
    int32_t* cn_off, int32_t* cn_len,
    int32_t* issuer_off, int32_t* issuer_len,
    int32_t* spki_off, int32_t* spki_len,
    int32_t* crldp_off, int32_t* crldp_len) {
  for (int64_t i = 0; i < n; ++i) {
    walker::Sidecar s =
        walker::extract_one(data + i * pad_len, pad_len, length[i]);
    ok[i] = s.ok;
    serial_off[i] = s.serial_off;
    serial_len[i] = s.serial_len;
    not_after_hour[i] = s.not_after_hour;
    is_ca[i] = s.is_ca;
    has_crldp[i] = s.has_crldp;
    cn_off[i] = s.cn_off;
    cn_len[i] = s.cn_len;
    issuer_off[i] = s.issuer_off;
    issuer_len[i] = s.issuer_len;
    spki_off[i] = s.spki_off;
    spki_len[i] = s.spki_len;
    crldp_off[i] = s.crldp_off;
    crldp_len[i] = s.crldp_len;
  }
}

}  // extern "C"

// Pack pre-decoded DER blobs (concatenated in `blob` with prefix-sum
// offsets) into the [n, pad_len] device layout. Returns count packed;
// lanes whose cert exceeds pad_len get length 0 and ok[i] = 0.
int64_t ctmr_pack_ders(
    int64_t n,
    const uint8_t* blob, const int64_t* off,
    int64_t pad_len,
    uint8_t* data, int32_t* length, uint8_t* okflags) {
  int64_t packed = 0;
  for (int64_t i = 0; i < n; ++i) {
    uint8_t* row = data + i * pad_len;
    std::memset(row, 0, (size_t)pad_len);
    int64_t len = off[i + 1] - off[i];
    if (len > pad_len) { length[i] = 0; okflags[i] = 0; continue; }
    std::memcpy(row, blob + off[i], (size_t)len);
    length[i] = (int32_t)len;
    okflags[i] = 1;
    ++packed;
  }
  return packed;
}

// ---------------------------------------------------------------------
// Multi-threaded entry points: each splits its batch into `threads`
// contiguous lane ranges (chunk t = lanes [n*t/T, n*(t+1)/T)) and runs
// the serial function above on each range through the persistent
// worker pool. Every per-lane output is written by exactly one chunk
// into its own row range, so data/length/ts/entry_ty/status (and every
// sidecar array) are BIT-IDENTICAL to the serial pass regardless of
// thread scheduling. The only shared-accumulator outputs — the issuer
// dedup buffer and its spans — are made deterministic by partitioning:
// chunk t appends into its own issuer_buf slice [t*cap/T, (t+1)*cap/T)
// with a chunk-local dedup table, and the Python caller merges the
// per-chunk groups by DER bytes in chunk order (= lane order), which
// reproduces the serial first-appearance group order exactly.

int64_t ctmr_decode_entries_mt(
    int64_t n,
    const char* li_buf, const int64_t* li_off,
    const char* ed_buf, const int64_t* ed_off,
    int64_t pad_len,
    uint8_t* data, int32_t* length,
    int64_t* ts_ms, int32_t* entry_ty,
    uint8_t* issuer_buf, int64_t issuer_cap,
    int64_t* issuer_off, int32_t* issuer_len,
    int32_t* status,
    uint8_t* scratch, int64_t scratch_each,  // scratch holds T spans
    int64_t threads, int64_t* chunk_used /* [threads] out */) {
  if (n <= 0) return 0;
  int T = (int)threads;
  if (T < 1) T = 1;
  if ((int64_t)T > n) T = (int)n;
  int64_t iss_each = issuer_cap / T;
  pool::WorkerPool::get().run(T, T, [&](int t) {
    int64_t lo = n * t / T, hi = n * (t + 1) / T;
    int64_t base = (int64_t)t * iss_each;
    // li_off/ed_off entries are absolute offsets into the shared
    // buffers, so passing the shifted pointer re-bases lane indexing
    // while byte addressing stays global.
    int64_t used = ctmr_decode_entries(
        hi - lo, li_buf, li_off + lo, ed_buf, ed_off + lo, pad_len,
        data + lo * pad_len, length + lo, ts_ms + lo, entry_ty + lo,
        issuer_buf + base, iss_each, issuer_off + lo, issuer_len + lo,
        status + lo, scratch + (int64_t)t * scratch_each, scratch_each);
    if (used >= 0) {
      // Chunk-local spans → global offsets into the shared buffer.
      for (int64_t i = lo; i < hi; ++i) {
        if (issuer_len[i] > 0) issuer_off[i] += base;
      }
    }
    chunk_used[t] = used;
  });
  for (int t = T; t < (int)threads; ++t) chunk_used[t] = 0;
  int64_t total = 0;
  for (int t = 0; t < T; ++t) {
    if (chunk_used[t] < 0) return -1;  // a chunk's issuer slice overflowed
    total += chunk_used[t];
  }
  return total;
}

void ctmr_extract_sidecars_mt(
    int64_t n,
    const uint8_t* data, int64_t pad_len, const int32_t* length,
    uint8_t* ok,
    int32_t* serial_off, int32_t* serial_len,
    int32_t* not_after_hour,
    uint8_t* is_ca, uint8_t* has_crldp,
    int32_t* cn_off, int32_t* cn_len,
    int32_t* issuer_off, int32_t* issuer_len,
    int32_t* spki_off, int32_t* spki_len,
    int32_t* crldp_off, int32_t* crldp_len,
    int64_t threads) {
  if (n <= 0) return;
  int T = (int)threads;
  if (T < 1) T = 1;
  if ((int64_t)T > n) T = (int)n;
  pool::WorkerPool::get().run(T, T, [&](int t) {
    int64_t lo = n * t / T, hi = n * (t + 1) / T;
    ctmr_extract_sidecars(
        hi - lo, data + lo * pad_len, pad_len, length + lo,
        ok + lo, serial_off + lo, serial_len + lo, not_after_hour + lo,
        is_ca + lo, has_crldp + lo, cn_off + lo, cn_len + lo,
        issuer_off + lo, issuer_len + lo, spki_off + lo, spki_len + lo,
        crldp_off + lo, crldp_len + lo);
  });
}

int64_t ctmr_pack_ders_mt(
    int64_t n,
    const uint8_t* blob, const int64_t* off,
    int64_t pad_len,
    uint8_t* data, int32_t* length, uint8_t* okflags,
    int64_t threads) {
  if (n <= 0) return 0;
  int T = (int)threads;
  if (T < 1) T = 1;
  if ((int64_t)T > n) T = (int)n;
  std::vector<int64_t> packed((size_t)T, 0);
  pool::WorkerPool::get().run(T, T, [&](int t) {
    int64_t lo = n * t / T, hi = n * (t + 1) / T;
    packed[(size_t)t] = ctmr_pack_ders(
        hi - lo, blob, off + lo, pad_len,
        data + lo * pad_len, length + lo, okflags + lo);
  });
  int64_t total = 0;
  for (int t = 0; t < T; ++t) total += packed[(size_t)t];
  return total;
}

// Pool introspection (the ingest.decode_threads gauge reads it).
int64_t ctmr_pool_threads() {
  return pool::WorkerPool::get().active_workers();
}

}  // extern "C"

// ---------------------------------------------------------------------
// Embedded-SCT extraction (round 13): the host half of the signature
// verification lane. A PLAIN byte-wise DER walk (no word windows — the
// consumer is the host, not the device walker) that must stay in exact
// lockstep with the python mirror ct_mapreduce_tpu/verify/sct.py:
// same TLV acceptance, same SCT-list bounds, same splice-digest
// convention, same ok/fallback classification. Parity is pinned by
// tests/test_ecdsa.py's extraction fuzz.

namespace sctext {

// FIPS 180-4 SHA-256, incremental (the signed payload is streamed:
// header ‖ der-before-ext ‖ der-after-ext ‖ extensions).
struct Sha256 {
  uint32_t h[8];
  uint8_t buf[64];
  uint64_t total = 0;
  int fill = 0;
  Sha256() {
    static const uint32_t h0[8] = {
        0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
        0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
    for (int i = 0; i < 8; ++i) h[i] = h0[i];
  }
  static uint32_t rotr(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
  }
  void block(const uint8_t* p) {
    static const uint32_t K[64] = {
        0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
        0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
        0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
        0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
        0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
        0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
        0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
        0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
        0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
        0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
        0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
        0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
        0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};
    uint32_t w[64];
    for (int t = 0; t < 16; ++t)
      w[t] = (uint32_t(p[4 * t]) << 24) | (uint32_t(p[4 * t + 1]) << 16) |
             (uint32_t(p[4 * t + 2]) << 8) | uint32_t(p[4 * t + 3]);
    for (int t = 16; t < 64; ++t) {
      uint32_t s0 = rotr(w[t - 15], 7) ^ rotr(w[t - 15], 18) ^ (w[t - 15] >> 3);
      uint32_t s1 = rotr(w[t - 2], 17) ^ rotr(w[t - 2], 19) ^ (w[t - 2] >> 10);
      w[t] = w[t - 16] + s0 + w[t - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int t = 0; t < 64; ++t) {
      uint32_t s1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + s1 + ch + K[t] + w[t];
      uint32_t s0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = s0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }
  void update(const uint8_t* p, int64_t len) {
    total += (uint64_t)len;
    while (len > 0) {
      int take = 64 - fill;
      if (take > len) take = (int)len;
      for (int i = 0; i < take; ++i) buf[fill + i] = p[i];
      fill += take; p += take; len -= take;
      if (fill == 64) { block(buf); fill = 0; }
    }
  }
  void finish(uint8_t out[32]) {
    uint64_t bits = total * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t z = 0;
    while (fill != 56) update(&z, 1);
    uint8_t lb[8];
    for (int i = 0; i < 8; ++i) lb[i] = (uint8_t)(bits >> (56 - 8 * i));
    update(lb, 8);
    for (int i = 0; i < 8; ++i)
      for (int j = 0; j < 4; ++j)
        out[4 * i + j] = (uint8_t)(h[i] >> (24 - 8 * j));
  }
};

struct Tlv {
  int tag = 0;
  int64_t off = 0, len = 0;
  bool ok = false;
};

// Mirror of sct.py::_tlv — definite lengths, 1..4 length octets.
inline Tlv tlv(const uint8_t* d, int64_t off, int64_t end) {
  Tlv t;
  if (off + 2 > end) return t;
  t.tag = d[off];
  int first = d[off + 1];
  int64_t p = off + 2;
  if (first < 0x80) {
    t.len = first;
  } else {
    int nb = first & 0x7f;
    if (nb == 0 || nb > 4 || p + nb > end) return t;
    int64_t v = 0;
    for (int i = 0; i < nb; ++i) v = (v << 8) | d[p + i];
    p += nb;
    t.len = v;
  }
  if (p + t.len > end) return t;
  t.off = p;
  t.ok = true;
  return t;
}

static const uint8_t kSctOid[10] = {0x2b, 0x06, 0x01, 0x04, 0x01,
                                    0xd6, 0x79, 0x02, 0x04, 0x02};
// 1.3.6.1.4.1.11129.2.4.3 — the precert poison (RFC 6962 §3.1),
// stripped alongside the SCT list during TBS reconstruction.
static const uint8_t kPoisonOid[10] = {0x2b, 0x06, 0x01, 0x04, 0x01,
                                       0xd6, 0x79, 0x02, 0x04, 0x03};

struct ExtWin {
  int64_t tlv_off = 0, tlv_end = 0, val_off = 0, val_end = 0;
  bool found = false;
};

// Mirror of sct.py::find_sct_extension.
inline ExtWin find_sct_extension(const uint8_t* d, int64_t n) {
  ExtWin w;
  Tlv t = tlv(d, 0, n);
  if (!t.ok || t.tag != 0x30) return w;
  Tlv tbs = tlv(d, t.off, t.off + t.len);
  if (!tbs.ok || tbs.tag != 0x30) return w;
  int64_t end = tbs.off + tbs.len;
  int64_t off = tbs.off;
  Tlv e = tlv(d, off, end);
  if (!e.ok) return w;
  if (e.tag == 0xa0) off = e.off + e.len;
  for (int i = 0; i < 6; ++i) {
    e = tlv(d, off, end);
    if (!e.ok) return w;
    off = e.off + e.len;
  }
  int64_t c_off = 0, c_len = 0;
  bool got = false;
  while (off < end) {
    e = tlv(d, off, end);
    if (!e.ok) return w;
    if (e.tag == 0xa3) { c_off = e.off; c_len = e.len; got = true; break; }
    off = e.off + e.len;
  }
  if (!got) return w;
  Tlv seq = tlv(d, c_off, c_off + c_len);
  if (!seq.ok || seq.tag != 0x30) return w;
  off = seq.off;
  end = seq.off + seq.len;
  while (off < end) {
    Tlv ext = tlv(d, off, end);
    if (!ext.ok || ext.tag != 0x30) return w;
    int64_t ext_end = ext.off + ext.len;
    Tlv oid = tlv(d, ext.off, ext_end);
    if (!oid.ok || oid.tag != 0x06) return w;
    bool is_sct = oid.len == 10 && std::memcmp(d + oid.off, kSctOid, 10) == 0;
    int64_t p = oid.off + oid.len;
    Tlv v = tlv(d, p, ext_end);
    if (v.ok && v.tag == 0x01) {  // critical BOOLEAN
      p = v.off + v.len;
      v = tlv(d, p, ext_end);
    }
    if (!v.ok || v.tag != 0x04) return w;
    if (is_sct) {
      w.tlv_off = off; w.tlv_end = ext_end;
      w.val_off = v.off; w.val_end = v.off + v.len;
      w.found = true;
      return w;
    }
    off = ext_end;
  }
  return w;
}

struct SctFields {
  const uint8_t* log_id = nullptr;
  int64_t timestamp = 0;
  const uint8_t* ext = nullptr;
  int64_t ext_len = 0;
  int hash_alg = 0, sig_alg = 0, version = 0;
  const uint8_t* sig = nullptr;
  int64_t sig_len = 0;
  bool ok = false;
};

// Mirror of sct.py::parse_sct_list (first SCT only).
inline SctFields parse_sct_list(const uint8_t* b, int64_t n) {
  SctFields f;
  if (n < 2) return f;
  int64_t total = ((int64_t)b[0] << 8) | b[1];
  if (total + 2 > n || total < 2) return f;
  int64_t n0 = ((int64_t)b[2] << 8) | b[3];
  int64_t p = 4;
  if (p + n0 > n || n0 < 47) return f;
  int64_t end = p + n0;
  f.version = b[p];
  f.log_id = b + p + 1;
  f.timestamp = 0;
  for (int i = 0; i < 8; ++i)
    f.timestamp = (f.timestamp << 8) | b[p + 33 + i];
  f.ext_len = ((int64_t)b[p + 41] << 8) | b[p + 42];
  int64_t q = p + 43;
  if (q + f.ext_len + 4 > end) return f;
  f.ext = b + q;
  q += f.ext_len;
  f.hash_alg = b[q];
  f.sig_alg = b[q + 1];
  int64_t sl = ((int64_t)b[q + 2] << 8) | b[q + 3];
  q += 4;
  if (q + sl != end) return f;
  f.sig = b + q;
  f.sig_len = sl;
  f.ok = true;
  return f;
}

// Mirror of sct.py::parse_ecdsa_sig with max_bytes = 32: big-endian
// 32-byte outputs, or false (fallback lane). Staged through locals —
// the python parser accepts or rejects the whole signature at once,
// so a failure after r parsed must leave r_out untouched (partial
// writes would diverge from the mirror on fallback lanes).
inline bool parse_ecdsa_sig32(const uint8_t* s, int64_t n,
                              uint8_t* r_out, uint8_t* s_out) {
  Tlv seq = tlv(s, 0, n);
  if (!seq.ok || seq.tag != 0x30 || seq.off + seq.len != n) return false;
  int64_t off = seq.off, end = seq.off + seq.len;
  uint8_t vals[2][32];
  for (int k = 0; k < 2; ++k) {
    Tlv v = tlv(s, off, end);
    if (!v.ok || v.tag != 0x02 || v.len < 1) return false;
    int64_t a = v.off, b = v.off + v.len;
    // python: content.lstrip(b"\x00") or b"\x00" — strip every
    // leading zero but keep one byte for the all-zero value.
    while (a < b - 1 && s[a] == 0) ++a;
    int64_t w = b - a;
    if (w > 32) return false;
    for (int i = 0; i < 32; ++i) vals[k][i] = 0;
    for (int64_t i = 0; i < w; ++i) vals[k][32 - w + i] = s[a + i];
    off = v.off + v.len;
  }
  if (off != end) return false;
  std::memcpy(r_out, vals[0], 32);
  std::memcpy(s_out, vals[1], 32);
  return true;
}

// Minimal-DER header (mirror of sct.py::_wrap_tlv): writes tag +
// length octets into out (<= 5 bytes), returns the header size.
inline int wrap_hdr(int tag, int64_t len, uint8_t* out) {
  out[0] = (uint8_t)tag;
  if (len < 0x80) { out[1] = (uint8_t)len; return 2; }
  if (len < 0x100) { out[1] = 0x81; out[2] = (uint8_t)len; return 3; }
  if (len < 0x10000) {
    out[1] = 0x82; out[2] = (uint8_t)(len >> 8); out[3] = (uint8_t)len;
    return 4;
  }
  out[1] = 0x83; out[2] = (uint8_t)(len >> 16);
  out[3] = (uint8_t)(len >> 8); out[4] = (uint8_t)len;
  return 5;
}

inline bool strip_oid(const uint8_t* d, const Tlv& oid) {
  return oid.len == 10 &&
         (std::memcmp(d + oid.off, kSctOid, 10) == 0 ||
          std::memcmp(d + oid.off, kPoisonOid, 10) == 0);
}

// RFC 6962 §3.2 signed payload, streamed (mirror of sct.py::
// sct_digest over reconstruct_precert_tbs, bit-identical — no
// materialized TBS buffer): header ‖ issuer_key_hash ‖ len3(tbs') ‖
// tbs' ‖ ext_len ‖ ext, where tbs' re-encodes the TBS with every
// SCT/poison extension removed and minimal lengths throughout.
// Returns false when the certificate doesn't parse to the extractor's
// acceptance (the caller then reports the lane as SCT_NONE, matching
// the python mirror).
inline bool digest_precert(const uint8_t* der, int64_t n,
                           const SctFields& f, const uint8_t* ikh,
                           uint8_t* out32) {
  Tlv cert = tlv(der, 0, n);
  if (!cert.ok || cert.tag != 0x30) return false;
  Tlv tbs = tlv(der, cert.off, cert.off + cert.len);
  if (!tbs.ok || tbs.tag != 0x30) return false;
  int64_t tbs_end = tbs.off + tbs.len;
  int64_t off = tbs.off;
  Tlv e = tlv(der, off, tbs_end);
  if (!e.ok) return false;
  if (e.tag == 0xa0) off = e.off + e.len;
  for (int i = 0; i < 6; ++i) {
    e = tlv(der, off, tbs_end);
    if (!e.ok) return false;
    off = e.off + e.len;
  }
  int64_t a3_off = -1, a3_end = 0, seq_off = 0, seq_len = 0;
  while (off < tbs_end) {
    e = tlv(der, off, tbs_end);
    if (!e.ok) return false;
    if (e.tag == 0xa3) {
      a3_off = off;
      a3_end = e.off + e.len;
      Tlv seq = tlv(der, e.off, a3_end);
      if (!seq.ok || seq.tag != 0x30) return false;
      seq_off = seq.off;
      seq_len = seq.len;
      break;
    }
    off = e.off + e.len;
  }
  // Pass 1: surviving extensions content length.
  int64_t kept_len = 0;
  if (a3_off >= 0) {
    int64_t p = seq_off, p_end = seq_off + seq_len;
    while (p < p_end) {
      Tlv ext = tlv(der, p, p_end);
      if (!ext.ok || ext.tag != 0x30) return false;
      int64_t ext_end = ext.off + ext.len;
      Tlv oid = tlv(der, ext.off, ext_end);
      if (!oid.ok || oid.tag != 0x06) return false;
      if (!strip_oid(der, oid)) kept_len += ext_end - p;
      p = ext_end;
    }
  }
  uint8_t seq_hdr[5], a3_hdr[5], tbs_hdr[5];
  int seq_hl = 0, a3_hl = 0;
  int64_t a3_total = 0;
  if (a3_off >= 0 && kept_len > 0) {
    seq_hl = wrap_hdr(0x30, kept_len, seq_hdr);
    a3_hl = wrap_hdr(0xa3, seq_hl + kept_len, a3_hdr);
    a3_total = a3_hl + seq_hl + kept_len;
  }
  int64_t content_len =
      a3_off >= 0
          ? (a3_off - tbs.off) + a3_total + (tbs_end - a3_end)
          : tbs.len;
  int tbs_hl = wrap_hdr(0x30, content_len, tbs_hdr);
  int64_t tbs_total = tbs_hl + content_len;

  Sha256 sha;
  uint8_t hdr[12];
  hdr[0] = 0; hdr[1] = 0;
  for (int j = 0; j < 8; ++j)
    hdr[2 + j] = (uint8_t)((uint64_t)f.timestamp >> (56 - 8 * j));
  hdr[10] = 0; hdr[11] = 1;
  sha.update(hdr, 12);
  sha.update(ikh, 32);
  uint8_t l3[3] = {(uint8_t)(tbs_total >> 16), (uint8_t)(tbs_total >> 8),
                   (uint8_t)tbs_total};
  sha.update(l3, 3);
  sha.update(tbs_hdr, tbs_hl);
  if (a3_off >= 0) {
    sha.update(der + tbs.off, a3_off - tbs.off);
    if (kept_len > 0) {
      sha.update(a3_hdr, a3_hl);
      sha.update(seq_hdr, seq_hl);
      // Pass 2: stream the surviving extension TLVs.
      int64_t p = seq_off, p_end = seq_off + seq_len;
      while (p < p_end) {
        Tlv ext = tlv(der, p, p_end);
        int64_t ext_end = ext.off + ext.len;
        Tlv oid = tlv(der, ext.off, ext_end);
        if (!strip_oid(der, oid)) sha.update(der + p, ext_end - p);
        p = ext_end;
      }
    }
    sha.update(der + a3_end, tbs_end - a3_end);
  } else {
    sha.update(der + tbs.off, tbs.len);
  }
  uint8_t el[2] = {(uint8_t)(f.ext_len >> 8), (uint8_t)f.ext_len};
  sha.update(el, 2);
  sha.update(f.ext, f.ext_len);
  sha.finish(out32);
  return true;
}

}  // namespace sctext

extern "C" {

// Embedded-SCT tuples for a packed row batch: status (0 none /
// 1 device-ready P-256 / 2 host-fallback), the RFC 6962 precert
// digest (round 24 — reconstructed TBS + per-lane issuer_key_hash),
// log id, timestamp, and big-endian r/s for status-1 lanes. Keep in
// lockstep with ct_mapreduce_tpu/verify/sct.py (extract_sct_lane).
// issuer_key_hash: [n, 32] per-lane SHA-256(issuer SPKI), or null
// (every lane hashes as all-zero — no issuer chain).
void ctmr_extract_scts_v2(
    int64_t n,
    const uint8_t* data, int64_t pad_len,
    const int32_t* length,
    const uint8_t* issuer_key_hash,  // [n, 32] or null
    uint8_t* ok,
    uint8_t* digest,      // [n, 32]
    uint8_t* log_id,      // [n, 32]
    int64_t* timestamp_ms,
    uint8_t* r_out,       // [n, 32]
    uint8_t* s_out,       // [n, 32]
    uint8_t* hash_alg,
    uint8_t* sig_alg) {
  static const uint8_t kZeroIkh[32] = {0};
  for (int64_t i = 0; i < n; ++i) {
    ok[i] = 0;
    int64_t len = length[i];
    if (len <= 0 || len > pad_len) continue;
    const uint8_t* der = data + i * pad_len;
    sctext::ExtWin w = sctext::find_sct_extension(der, len);
    if (!w.found) continue;
    sctext::SctFields f =
        sctext::parse_sct_list(der + w.val_off, w.val_end - w.val_off);
    if (!f.ok) continue;
    const uint8_t* ikh =
        issuer_key_hash ? issuer_key_hash + i * 32 : kZeroIkh;
    if (!sctext::digest_precert(der, len, f, ikh, digest + i * 32))
      continue;
    for (int j = 0; j < 32; ++j) log_id[i * 32 + j] = f.log_id[j];
    timestamp_ms[i] = f.timestamp;
    hash_alg[i] = (uint8_t)f.hash_alg;
    sig_alg[i] = (uint8_t)f.sig_alg;
    if (f.version != 0 || f.hash_alg != 4 || f.sig_alg != 3) {
      ok[i] = 2;
      continue;
    }
    if (!sctext::parse_ecdsa_sig32(f.sig, f.sig_len, r_out + i * 32,
                                   s_out + i * 32)) {
      ok[i] = 2;
      continue;
    }
    ok[i] = 1;
  }
}

void ctmr_extract_scts_v2_mt(
    int64_t n,
    const uint8_t* data, int64_t pad_len,
    const int32_t* length,
    const uint8_t* issuer_key_hash,
    uint8_t* ok, uint8_t* digest, uint8_t* log_id,
    int64_t* timestamp_ms, uint8_t* r_out, uint8_t* s_out,
    uint8_t* hash_alg, uint8_t* sig_alg,
    int64_t threads) {
  if (n <= 0) return;
  int T = (int)threads;
  if (T < 1) T = 1;
  if ((int64_t)T > n) T = (int)n;
  pool::WorkerPool::get().run(T, T, [&](int t) {
    int64_t lo = n * t / T, hi = n * (t + 1) / T;
    ctmr_extract_scts_v2(
        hi - lo, data + lo * pad_len, pad_len, length + lo,
        issuer_key_hash ? issuer_key_hash + lo * 32 : nullptr,
        ok + lo, digest + lo * 32, log_id + lo * 32, timestamp_ms + lo,
        r_out + lo * 32, s_out + lo * 32, hash_alg + lo, sig_alg + lo);
  });
}

}  // extern "C"
