"""Native host-side ingest accelerator (C++ via ctypes).

Loads (building on first use, cached beside the source) the compiled
batch decoder in :file:`ctmr_native.cpp`. Everything degrades to the
pure-Python lanes when no compiler is available — the native path is a
throughput optimization, never a correctness dependency.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "ctmr_native.cpp")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LOAD_FAILED = False


def _so_path() -> str:
    # Cache beside the source when writable, else in ~/.cache.
    if os.access(_HERE, os.W_OK):
        return os.path.join(_HERE, "libctmr_native.so")
    cache = os.path.join(
        os.path.expanduser("~"), ".cache", "ct_mapreduce_tpu"
    )
    os.makedirs(cache, exist_ok=True)
    return os.path.join(cache, "libctmr_native.so")


def _build(so: str) -> bool:
    # Compile to a temp path and rename atomically — a concurrent
    # process must never dlopen a half-written .so.
    tmp = f"{so}.build.{os.getpid()}"
    for cxx in ("g++", "c++", "clang++"):
        try:
            res = subprocess.run(
                [cxx, "-O3", "-shared", "-fPIC", "-std=c++17",
                 "-pthread", "-o", tmp, _SRC],
                capture_output=True, timeout=240,
            )
        except (FileNotFoundError, subprocess.TimeoutExpired):
            continue
        if res.returncode == 0:
            os.replace(tmp, so)
            return True
    if os.path.exists(tmp):
        os.unlink(tmp)
    return False


def load() -> Optional[ctypes.CDLL]:
    """The shared library, or None when unavailable (no compiler)."""
    global _LIB, _LOAD_FAILED
    if _LIB is not None or _LOAD_FAILED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _LOAD_FAILED:
            return _LIB
        so = _so_path()
        if (not os.path.exists(so)
                or os.path.getmtime(so) < os.path.getmtime(_SRC)):
            if not _build(so):
                _LOAD_FAILED = True
                return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            _LOAD_FAILED = True
            return None
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.ctmr_decode_entries.restype = ctypes.c_int64
        lib.ctmr_decode_entries.argtypes = [
            ctypes.c_int64,
            ctypes.c_char_p, i64p,
            ctypes.c_char_p, i64p,
            ctypes.c_int64,
            u8p, i32p,
            i64p, i32p,
            u8p, ctypes.c_int64,
            i64p, i32p,
            i32p,
            u8p, ctypes.c_int64,
        ]
        lib.ctmr_extract_sidecars.restype = None
        lib.ctmr_extract_sidecars.argtypes = [
            ctypes.c_int64,
            u8p, ctypes.c_int64, i32p,
            u8p,
            i32p, i32p,
            i32p,
            u8p, u8p,
            i32p, i32p,
            i32p, i32p,
            i32p, i32p,
            i32p, i32p,
        ]
        lib.ctmr_pack_ders.restype = ctypes.c_int64
        lib.ctmr_pack_ders.argtypes = [
            ctypes.c_int64,
            u8p, i64p,
            ctypes.c_int64,
            u8p, i32p, u8p,
        ]
        # Multi-threaded entry points (worker-pool lane-range split).
        # A prebuilt .so from before the pool existed may lack them —
        # the mtime check rebuilds from source when possible, but a
        # compiler-less host with a stale cached library must still
        # load: callers check `has_mt` and stay on the serial paths.
        try:
            lib.ctmr_decode_entries_mt.restype = ctypes.c_int64
            lib.ctmr_decode_entries_mt.argtypes = [
                ctypes.c_int64,
                ctypes.c_char_p, i64p,
                ctypes.c_char_p, i64p,
                ctypes.c_int64,
                u8p, i32p,
                i64p, i32p,
                u8p, ctypes.c_int64,
                i64p, i32p,
                i32p,
                u8p, ctypes.c_int64,
                ctypes.c_int64, i64p,
            ]
            lib.ctmr_extract_sidecars_mt.restype = None
            lib.ctmr_extract_sidecars_mt.argtypes = [
                ctypes.c_int64,
                u8p, ctypes.c_int64, i32p,
                u8p,
                i32p, i32p,
                i32p,
                u8p, u8p,
                i32p, i32p,
                i32p, i32p,
                i32p, i32p,
                i32p, i32p,
                ctypes.c_int64,
            ]
            lib.ctmr_pack_ders_mt.restype = ctypes.c_int64
            lib.ctmr_pack_ders_mt.argtypes = [
                ctypes.c_int64,
                u8p, i64p,
                ctypes.c_int64,
                u8p, i32p, u8p,
                ctypes.c_int64,
            ]
            lib.ctmr_pool_threads.restype = ctypes.c_int64
            lib.ctmr_pool_threads.argtypes = []
            lib.has_mt = True
        except AttributeError:
            lib.has_mt = False
        # SCT extraction (round 13; _v2 since round 24 — the RFC 6962
        # precert digest takes a per-lane issuer_key_hash input, so the
        # symbol is renamed: a stale pre-round-24 .so lacks it and
        # degrades to the python extractor instead of being called with
        # a mismatched signature). Same stale-library contract as
        # has_mt: callers check `has_sct`.
        try:
            lib.ctmr_extract_scts_v2.restype = None
            lib.ctmr_extract_scts_v2.argtypes = [
                ctypes.c_int64,
                u8p, ctypes.c_int64, i32p,
                u8p,
                u8p,
                u8p, u8p,
                i64p,
                u8p, u8p,
                u8p, u8p,
            ]
            lib.ctmr_extract_scts_v2_mt.restype = None
            lib.ctmr_extract_scts_v2_mt.argtypes = (
                lib.ctmr_extract_scts_v2.argtypes + [ctypes.c_int64]
            )
            lib.has_sct = True
        except AttributeError:
            lib.has_sct = False
        _LIB = lib
        return _LIB


def available() -> bool:
    return load() is not None
