"""Production log-list loader: the Google/Apple log-list v3 JSON
schema → the verify lane's trust anchors.

The CT ecosystem publishes its trusted logs as a versioned JSON
document (``https://www.gstatic.com/ct/log_list/v3/log_list.json``;
Apple ships the same schema): operators, each with logs carrying

- ``log_id`` — base64 of SHA-256 over the log's SubjectPublicKeyInfo
  DER (RFC 6962 §3.2's key id);
- ``key`` — base64 SPKI DER itself;
- ``state`` — exactly one of ``pending`` / ``qualified`` / ``usable``
  / ``readonly`` / ``retired`` / ``rejected``, keyed by name with a
  timestamp object;
- ``temporal_interval`` — optional shard window
  (``start_inclusive``/``end_exclusive``, RFC 3339): the shard only
  accepts certs expiring inside it, and an SCT should be checked
  against the shard that was accepting at its timestamp.

:func:`load_log_list` parses that schema into
:class:`AuditLogList`: every log's SPKI is decoded (EC P-256/P-384
and RSA — the only key types the ecosystem uses) into the
``LogKeyRegistry`` entry shape the verify lane already consumes, and
``log_id == SHA-256(SPKI)`` is enforced LOUDLY (a key/log_id mismatch
is a poisoned trust anchor, never a skippable row). Temporal-shard
routing and state flags ride each entry, surfaced through
:meth:`AuditLogList.route`.

Fixture side: :func:`spki_from_signer` + :func:`fixture_log_list`
emit the SAME production schema for the deterministic test signers,
with log_id properly derived from the SPKI — the recorded-shard
corpus (audit/driver.py) is signed by keys published exactly the way
production logs publish theirs.
"""

from __future__ import annotations

import base64
import hashlib
import json
from dataclasses import dataclass, field
from typing import Optional

from ct_mapreduce_tpu.verify import host
from ct_mapreduce_tpu.verify.lane import LogKeyRegistry

# DER OID content bytes.
_OID_EC_PUBKEY = bytes.fromhex("2a8648ce3d0201")  # 1.2.840.10045.2.1
_OID_P256 = bytes.fromhex("2a8648ce3d030107")  # 1.2.840.10045.3.1.7
_OID_P384 = bytes.fromhex("2b81040022")  # 1.3.132.0.34
_OID_RSA = bytes.fromhex("2a864886f70d010101")  # 1.2.840.113549.1.1.1

KNOWN_STATES = ("pending", "qualified", "usable", "readonly",
                "retired", "rejected")


def _tlv(der: bytes, off: int, end: int):
    """Same TLV acceptance as verify/sct.py (definite lengths, <= 4
    length octets)."""
    if off + 2 > end:
        return None
    tag = der[off]
    first = der[off + 1]
    off += 2
    if first < 0x80:
        length = first
    else:
        nb = first & 0x7F
        if nb == 0 or nb > 4 or off + nb > end:
            return None
        length = int.from_bytes(der[off:off + nb], "big")
        off += nb
    if off + length > end:
        return None
    return tag, off, length


def _wrap(tag: int, content: bytes) -> bytes:
    n = len(content)
    if n < 0x80:
        return bytes([tag, n]) + content
    if n < 0x100:
        return bytes([tag, 0x81, n]) + content
    return bytes([tag, 0x82, n >> 8, n & 0xFF]) + content


def parse_spki(spki: bytes) -> dict:
    """SubjectPublicKeyInfo DER → a LogKeyRegistry-shaped key dict
    (without ``log_id``): ``{"alg": "p256"|"p384", "x", "y"}`` or
    ``{"alg": "rsa", "n", "e"}``. Raises ValueError on anything else
    — an undecodable trust anchor must never load silently."""
    n = len(spki)
    t = _tlv(spki, 0, n)
    if t is None or t[0] != 0x30 or t[1] + t[2] != n:
        raise ValueError("SPKI is not a DER SEQUENCE")
    off, end = t[1], t[1] + t[2]
    alg = _tlv(spki, off, end)
    if alg is None or alg[0] != 0x30:
        raise ValueError("SPKI missing AlgorithmIdentifier")
    a_off, a_end = alg[1], alg[1] + alg[2]
    oid = _tlv(spki, a_off, a_end)
    if oid is None or oid[0] != 0x06:
        raise ValueError("AlgorithmIdentifier missing OID")
    alg_oid = spki[oid[1]:oid[1] + oid[2]]
    bits = _tlv(spki, alg[1] + alg[2], end)
    if bits is None or bits[0] != 0x03 or bits[2] < 2 \
            or spki[bits[1]] != 0x00:
        raise ValueError("SPKI missing subjectPublicKey BIT STRING")
    key = spki[bits[1] + 1:bits[1] + bits[2]]
    if alg_oid == _OID_EC_PUBKEY:
        curve_oid = _tlv(spki, oid[1] + oid[2], a_end)
        if curve_oid is None or curve_oid[0] != 0x06:
            raise ValueError("EC SPKI missing namedCurve OID")
        curve_bytes = spki[curve_oid[1]:curve_oid[1] + curve_oid[2]]
        if curve_bytes == _OID_P256:
            curve = host.P256
        elif curve_bytes == _OID_P384:
            curve = host.P384
        else:
            raise ValueError(
                f"unsupported EC curve OID {curve_bytes.hex()}")
        w = curve.byte_len
        if len(key) != 1 + 2 * w or key[0] != 0x04:
            raise ValueError(
                f"EC point must be uncompressed 0x04‖X‖Y "
                f"({1 + 2 * w} bytes), got {len(key)}")
        return {
            "alg": curve.name,
            "x": hex(int.from_bytes(key[1:1 + w], "big")),
            "y": hex(int.from_bytes(key[1 + w:], "big")),
        }
    if alg_oid == _OID_RSA:
        t = _tlv(key, 0, len(key))
        if t is None or t[0] != 0x30:
            raise ValueError("RSA key is not a DER SEQUENCE")
        r_off, r_end = t[1], t[1] + t[2]
        nv = _tlv(key, r_off, r_end)
        if nv is None or nv[0] != 0x02:
            raise ValueError("RSA key missing modulus INTEGER")
        ev = _tlv(key, nv[1] + nv[2], r_end)
        if ev is None or ev[0] != 0x02:
            raise ValueError("RSA key missing exponent INTEGER")
        return {
            "alg": "rsa",
            "n": hex(int.from_bytes(key[nv[1]:nv[1] + nv[2]], "big")),
            "e": hex(int.from_bytes(key[ev[1]:ev[1] + ev[2]], "big")),
        }
    raise ValueError(f"unsupported SPKI algorithm OID {alg_oid.hex()}")


def encode_ec_spki(x: int, y: int, curve: host.Curve) -> bytes:
    """EC SubjectPublicKeyInfo DER (uncompressed point) — the fixture
    side of :func:`parse_spki`, used to publish deterministic test
    signers through the production schema."""
    curve_oid = _OID_P256 if curve.name == "p256" else _OID_P384
    w = curve.byte_len
    point = b"\x04" + x.to_bytes(w, "big") + y.to_bytes(w, "big")
    return _wrap(0x30,
                 _wrap(0x30, _wrap(0x06, _OID_EC_PUBKEY)
                       + _wrap(0x06, curve_oid))
                 + _wrap(0x03, b"\x00" + point))


def encode_rsa_spki(n: int, e: int) -> bytes:
    def _int(v: int) -> bytes:
        b = v.to_bytes((v.bit_length() + 7) // 8 or 1, "big")
        if b[0] & 0x80:
            b = b"\x00" + b
        return _wrap(0x02, b)

    return _wrap(0x30,
                 _wrap(0x30, _wrap(0x06, _OID_RSA) + _wrap(0x05, b""))
                 + _wrap(0x03, b"\x00" + _wrap(0x30, _int(n) + _int(e))))


def parse_rfc3339_ms(ts: str) -> int:
    """RFC 3339 UTC timestamp → epoch milliseconds. The log-list
    schema uses Z-suffixed UTC exclusively."""
    import datetime as dt

    s = ts.replace("Z", "+00:00")
    d = dt.datetime.fromisoformat(s)
    if d.tzinfo is None:
        d = d.replace(tzinfo=dt.timezone.utc)
    return int(d.timestamp() * 1000)


@dataclass
class LogShard:
    """One log (= one temporal shard when the operator shards) from
    the list: the registry entry plus routing metadata."""

    log_id: bytes  # 32 raw bytes, == SHA-256(SPKI)
    entry: dict  # LogKeyRegistry shape (log_id hex + alg + coords)
    operator: str
    description: str
    url: str
    state: str  # one of KNOWN_STATES
    state_timestamp_ms: int
    interval_start_ms: Optional[int]  # inclusive, None = unsharded
    interval_end_ms: Optional[int]  # exclusive

    def accepts_at(self, timestamp_ms: int) -> bool:
        """Temporal-shard routing: inclusive start, exclusive end
        (the schema's ``start_inclusive``/``end_exclusive``)."""
        if self.interval_start_ms is not None \
                and timestamp_ms < self.interval_start_ms:
            return False
        if self.interval_end_ms is not None \
                and timestamp_ms >= self.interval_end_ms:
            return False
        return True


@dataclass
class RouteVerdict:
    """Where an SCT's (log_id, timestamp) lands against the list."""

    known: bool
    state: str = ""
    operator: str = ""
    in_interval: bool = False
    retired: bool = False


@dataclass
class AuditLogList:
    """The parsed list: shards by log_id + the registry the verify
    lane loads. ``route`` implements the audit policy — verify
    against the key regardless of state (a retired log's old SCTs
    are still cryptographically checkable), but FLAG retired logs and
    out-of-interval timestamps so the driver can count them."""

    shards: dict[bytes, LogShard] = field(default_factory=dict)
    version: str = ""
    log_list_timestamp: str = ""

    def registry(self) -> LogKeyRegistry:
        reg = LogKeyRegistry()
        for shard in self.shards.values():
            reg.register(shard.entry)
        return reg

    def route(self, log_id: bytes, timestamp_ms: int) -> RouteVerdict:
        shard = self.shards.get(log_id)
        if shard is None:
            return RouteVerdict(known=False)
        return RouteVerdict(
            known=True,
            state=shard.state,
            operator=shard.operator,
            in_interval=shard.accepts_at(timestamp_ms),
            retired=shard.state == "retired",
        )

    def __len__(self) -> int:
        return len(self.shards)


def _parse_log(raw: dict, operator: str) -> LogShard:
    key_b64 = raw.get("key", "")
    logid_b64 = raw.get("log_id", "")
    if not key_b64 or not logid_b64:
        raise ValueError(
            f"log {raw.get('description', '?')!r} ({operator}): "
            "missing key or log_id")
    spki = base64.b64decode(key_b64)
    log_id = base64.b64decode(logid_b64)
    computed = hashlib.sha256(spki).digest()
    if log_id != computed:
        # The loud rejection: a list whose key doesn't hash to its
        # log_id is corrupt or tampered — refusing the whole load is
        # the only safe behavior for a trust anchor.
        raise ValueError(
            f"log {raw.get('description', '?')!r} ({operator}): "
            f"log_id {log_id.hex()} != SHA-256(key) {computed.hex()}")
    entry = parse_spki(spki)
    entry["log_id"] = log_id.hex()
    entry["operator"] = operator
    state_raw = raw.get("state", {})
    state, state_ts = "", 0
    for name in KNOWN_STATES:
        if name in state_raw:
            state = name
            ts = state_raw[name].get("timestamp", "")
            state_ts = parse_rfc3339_ms(ts) if ts else 0
            break
    interval = raw.get("temporal_interval") or {}
    start = interval.get("start_inclusive")
    end = interval.get("end_exclusive")
    return LogShard(
        log_id=log_id,
        entry=entry,
        operator=operator,
        description=raw.get("description", ""),
        url=raw.get("url", ""),
        state=state,
        state_timestamp_ms=state_ts,
        interval_start_ms=parse_rfc3339_ms(start) if start else None,
        interval_end_ms=parse_rfc3339_ms(end) if end else None,
    )


def parse_log_list(doc: dict) -> AuditLogList:
    """Log-list v3 document → :class:`AuditLogList`. ``rejected`` and
    ``pending`` logs are skipped (their keys never signed anything the
    ecosystem accepted); every other state loads. Key/log_id
    mismatches raise."""
    out = AuditLogList(
        version=str(doc.get("version", "")),
        log_list_timestamp=str(doc.get("log_list_timestamp", "")),
    )
    for op in doc.get("operators", []):
        name = op.get("name", "")
        for raw in list(op.get("logs", [])) + list(
                op.get("tiled_logs", [])):
            shard = _parse_log(raw, name)
            if shard.state in ("rejected", "pending"):
                continue
            out.shards[shard.log_id] = shard
    return out


def load_log_list(path: str) -> AuditLogList:
    with open(path) as fh:
        return parse_log_list(json.load(fh))


# -- fixture side --------------------------------------------------------


def spki_from_signer(signer) -> bytes:
    """The SPKI DER of a fixture signer (EcSctSigner / RsaSctSigner) —
    what a production log would publish as its ``key``."""
    if hasattr(signer, "curve"):
        return encode_ec_spki(signer.q[0], signer.q[1], signer.curve)
    return encode_rsa_spki(signer.n, signer.e)


def production_log_id(signer) -> bytes:
    """RFC 6962 log id for a fixture signer: SHA-256 over its SPKI
    (NOT the ``ctmr-log-v1`` fixture id). Assigning this to
    ``signer.log_id`` makes the signer publishable through the
    production schema."""
    return hashlib.sha256(spki_from_signer(signer)).digest()


def adopt_production_id(signer):
    """Rewrite a fixture signer's log_id to the RFC derivation so its
    SCTs carry the id the production list maps to its key."""
    signer.log_id = production_log_id(signer)
    return signer


def fixture_log_list(logs: list[dict]) -> dict:
    """Build a production-schema v3 document for fixture signers.

    ``logs``: dicts with ``signer`` (already production-id adopted),
    ``operator``, ``description``, ``state`` (default "usable"),
    ``state_timestamp``, and optional ``interval`` =
    (start_inclusive, end_exclusive) RFC 3339 strings."""
    by_op: dict[str, list[dict]] = {}
    for spec in logs:
        signer = spec["signer"]
        spki = spki_from_signer(signer)
        log_id = hashlib.sha256(spki).digest()
        if signer.log_id != log_id:
            raise ValueError(
                "signer not production-id adopted "
                "(call adopt_production_id first)")
        raw = {
            "description": spec.get("description", "fixture log"),
            "log_id": base64.b64encode(log_id).decode(),
            "key": base64.b64encode(spki).decode(),
            "url": spec.get("url", "https://fixture.ct.example/"),
            "mmd": 86400,
            "state": {
                spec.get("state", "usable"): {
                    "timestamp": spec.get(
                        "state_timestamp", "2024-01-01T00:00:00Z"),
                },
            },
        }
        if spec.get("interval"):
            start, end = spec["interval"]
            raw["temporal_interval"] = {
                "start_inclusive": start,
                "end_exclusive": end,
            }
        by_op.setdefault(spec.get("operator", "Fixture Op"),
                         []).append(raw)
    return {
        "version": "3.99",
        "log_list_timestamp": "2026-01-01T00:00:00Z",
        "operators": [
            {"name": op, "email": [f"{op.lower().replace(' ', '-')}"
                                   "@ct.example"],
             "logs": logs_}
            for op, logs_ in sorted(by_op.items())
        ],
    }
