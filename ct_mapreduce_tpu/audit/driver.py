"""Recorded-shard audit pipeline: real-wire ``get-entries`` pages
through decode → RFC 6962 TBS-reconstructed verify → aggregate →
filter, with the quarantine lane in front.

The audit corpus is a **recorded shard** (``CTMRAU01``): a gzip JSON
capture of get-entries responses plus the production-schema log list
that verifies them, checked in so the whole audit path replays
deterministically with zero egress. ``--live`` mode substitutes the
existing :class:`~ct_mapreduce_tpu.ingest.ctclient.CTLogClient`
transport for the recorded pages — same pipeline from the first
decode on.

Per distinct page the driver runs a host pre-pass ONCE:

1. decode each entry (:func:`ct_mapreduce_tpu.ingest.leaf.
   decode_json_entry`) to the stored cert + chain issuer;
2. extract SCTs through the native scanner AND the Python mirror and
   diff them (:mod:`ct_mapreduce_tpu.audit.quarantine`): diverging
   lanes are spooled and DROPPED before the pipeline sees them;
3. route each surviving SCT's (log_id, timestamp) against the log
   list — unknown logs, retired logs (verify-but-flag), and
   out-of-shard-interval timestamps are tallied.

Surviving entries then ride the UNMODIFIED production sink
(:class:`~ct_mapreduce_tpu.ingest.sync.AggregatorSink` with
``verifySignatures`` on): native batch decode, device-lane ECDSA with
the per-issuer-group ikh threading, per-issuer verified/failed folds.
Tiling (``tile`` > 1) resubmits the recorded pages with shifted entry
indices so scale runs (1e5 tier-1 / 1e6 tool) exercise the full
decode+verify+aggregate path on every entry; the host pre-pass is
shared across tiles — byte-identical copies cannot diverge
differently, so re-checking them would measure nothing.

The aggregate then feeds every existing surface: ``storage_statistics``
per-issuer ``sctsVerified``/``sctsFailed``, the serve plane's
``/issuer`` meta, and CTMRCK02 checkpoints — the audit subsystem adds
no parallel bookkeeping.
"""

from __future__ import annotations

import base64
import gzip
import json
import time
from dataclasses import dataclass, field
from typing import Iterable, Optional

import numpy as np

from ct_mapreduce_tpu.audit import loglist as loglistlib
from ct_mapreduce_tpu.audit import quarantine as quarlib
from ct_mapreduce_tpu.ingest import leaf as leaflib
from ct_mapreduce_tpu.telemetry import metrics
from ct_mapreduce_tpu.verify import sct as sctlib

RECORDED_FORMAT = "CTMRAU01"


def load_recorded(path: str) -> dict:
    """A ``CTMRAU01`` recorded shard: ``{format, log_url, log_list,
    pages: [{start, entries: [{leaf_input, extra_data}]}]}`` —
    gzip-compressed JSON (the container needs nothing beyond the
    stdlib; zstd is deliberately not assumed)."""
    with gzip.open(path, "rt", encoding="utf-8") as fh:
        doc = json.load(fh)
    if doc.get("format") != RECORDED_FORMAT:
        raise ValueError(
            f"unknown recorded-shard format in {path}: "
            f"{doc.get('format')!r}")
    return doc


def write_recorded(path: str, doc: dict) -> None:
    doc = dict(doc, format=RECORDED_FORMAT)
    # mtime=0 + empty FNAME → byte-stable archive for identical
    # content (the checked-in fixture must not churn on regeneration
    # or embed the output path).
    with open(path, "wb") as raw, \
            gzip.GzipFile("", fileobj=raw, mode="wb", mtime=0) as fh:
        fh.write(json.dumps(doc, sort_keys=True).encode())


@dataclass
class PageAnalysis:
    """Host pre-pass result for one distinct page."""

    keep: list  # [(leaf_input_b64, extra_data_b64)] surviving lanes
    quarantined: int = 0
    sct_lanes: int = 0
    no_sct: int = 0
    decode_failed: int = 0
    unknown_log: int = 0
    retired: int = 0
    out_of_interval: int = 0
    per_log: dict = field(default_factory=dict)  # log_id hex -> lanes


@dataclass
class AuditReport:
    entries: int = 0
    pages: int = 0
    tile: int = 1
    quarantined: int = 0
    divergence_measured: bool = False
    sct_lanes: int = 0
    no_sct: int = 0
    decode_failed: int = 0
    unknown_log: int = 0
    retired: int = 0
    out_of_interval: int = 0
    verified: int = 0
    failed: int = 0
    verifier_no_sct: int = 0
    verifier_no_key: int = 0
    device_lanes: int = 0
    host_lanes: int = 0
    per_issuer: dict = field(default_factory=dict)  # id -> (v, f)
    per_log: dict = field(default_factory=dict)
    wall_s: float = 0.0

    def to_json(self) -> dict:
        out = {k: getattr(self, k) for k in (
            "entries", "pages", "tile", "quarantined",
            "divergence_measured", "sct_lanes", "no_sct",
            "decode_failed", "unknown_log", "retired",
            "out_of_interval", "verified", "failed",
            "verifier_no_sct", "verifier_no_key", "device_lanes",
            "host_lanes", "wall_s")}
        out["perIssuer"] = {k: list(v) for k, v in
                            sorted(self.per_issuer.items())}
        out["perLog"] = dict(sorted(self.per_log.items()))
        return out


class AuditDriver:
    """One audit run: a log list, a quarantine spool, and a fresh
    aggregation pipeline (verify lane on)."""

    def __init__(self, log_list: loglistlib.AuditLogList,
                 quarantine_dir: str = "",
                 capacity: int = 1 << 14, batch_size: int = 256,
                 flush_size: int = 256, batch_width: int = 0,
                 chunks_per_dispatch: int = 0,
                 filter_path: str = "", filter_fp: float = 0.01,
                 aggregator=None, sink=None):
        from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
        from ct_mapreduce_tpu.ingest.sync import AggregatorSink

        self.log_list = log_list
        self.spool = quarlib.QuarantineSpool(quarantine_dir)
        self.aggregator = aggregator or TpuAggregator(
            capacity=capacity, batch_size=batch_size)
        if filter_path:
            # Arm serial capture BEFORE ingestion (device-lane serials
            # folded earlier are hashes only); the artifact is emitted
            # at checkpoint-save time, same as the production sink.
            self.aggregator.configure_filter_emission(filter_path,
                                                      filter_fp)
        self.sink = sink or AggregatorSink(
            self.aggregator, flush_size=flush_size,
            device_queue_depth=0, verify_signatures=True,
            chunks_per_dispatch=chunks_per_dispatch)
        if batch_width:
            self.sink.verifier.batch_width = batch_width
        for shard in log_list.shards.values():
            self.sink.verifier.keys.register(dict(shard.entry))

    # -- host pre-pass ---------------------------------------------------
    def analyze_page(self, entries: list, start: int = 0,
                     log_url: str = "") -> PageAnalysis:
        """Decode, quarantine-check, and route ONE distinct page."""
        ana = PageAnalysis(keep=[])
        ders: list[bytes] = []
        ikhs: list[bytes] = []
        decoded_rows: list[int] = []
        for i, e in enumerate(entries):
            try:
                dec = leaflib.decode_json_entry(start + i, e)
            except leaflib.LeafDecodeError:
                # Undecodable entries still go to the sink — its native
                # decoder owns the error taxonomy; the pre-pass only
                # tracks that it had nothing to route.
                ana.decode_failed += 1
                ana.keep.append((e["leaf_input"],
                                 e.get("extra_data", "")))
                continue
            ders.append(dec.cert_der)
            ikhs.append(sctlib.issuer_key_hash_of(dec.issuer_der)
                        if dec.issuer_der else sctlib.ZERO_IKH)
            decoded_rows.append(i)
        if ders:
            pad = max(len(d) for d in ders)
            data = np.zeros((len(ders), pad), np.uint8)
            length = np.zeros((len(ders),), np.int32)
            for j, d in enumerate(ders):
                data[j, :len(d)] = np.frombuffer(d, np.uint8)
                length[j] = len(d)
            ikh = np.frombuffer(b"".join(ikhs), np.uint8).reshape(-1, 32)
            chk = quarlib.check_batch(data, length, issuer_key_hash=ikh)
            ana.quarantined = chk.count
            self._last_measured = chk.measured
            ext = sctlib.extract_scts_np(data, length,
                                         issuer_key_hash=ikh)
            for j, i in enumerate(decoded_rows):
                if chk.mask[j]:
                    self.spool.file(
                        ders[j], index=start + i, log_url=log_url,
                        reasons=chk.reasons.get(j, []))
                    continue
                e = entries[i]
                ana.keep.append((e["leaf_input"],
                                 e.get("extra_data", "")))
                if int(ext.ok[j]) == 0:
                    ana.no_sct += 1
                    continue
                ana.sct_lanes += 1
                log_id = bytes(ext.log_id[j])
                ana.per_log[log_id.hex()] = (
                    ana.per_log.get(log_id.hex(), 0) + 1)
                verdict = self.log_list.route(
                    log_id, int(ext.timestamp_ms[j]))
                if not verdict.known:
                    ana.unknown_log += 1
                    metrics.incr_counter("audit", "unknown_log")
                else:
                    if verdict.retired:
                        ana.retired += 1
                        metrics.incr_counter("audit", "retired_sct")
                    if not verdict.in_interval:
                        ana.out_of_interval += 1
                        metrics.incr_counter("audit", "out_of_interval")
        return ana

    # -- full runs -------------------------------------------------------
    def run_pages(self, pages: Iterable[tuple[int, list]],
                  log_url: str = "audit-log", tile: int = 1,
                  ) -> AuditReport:
        """Audit pages ``(start_index, entries)``; each distinct page
        is pre-passed once and submitted ``tile`` times with shifted
        indices."""
        from ct_mapreduce_tpu.ingest.sync import RawBatch

        t0 = time.monotonic()
        rep = AuditReport(tile=tile)
        analyses: list[tuple[int, PageAnalysis]] = []
        self._last_measured = False
        total_span = 0
        for start, entries in pages:
            ana = self.analyze_page(entries, start=start,
                                    log_url=log_url)
            analyses.append((start, ana))
            rep.pages += 1
            total_span = max(total_span, start + len(entries))
            for name in ("quarantined", "sct_lanes", "no_sct",
                         "decode_failed", "unknown_log", "retired",
                         "out_of_interval"):
                setattr(rep, name, getattr(rep, name) + getattr(ana, name))
            for k, v in ana.per_log.items():
                rep.per_log[k] = rep.per_log.get(k, 0) + v
        rep.divergence_measured = self._last_measured
        # The pre-pass tallies cover one tile; scale-out copies behave
        # identically by construction.
        for name in ("sct_lanes", "no_sct", "decode_failed",
                     "unknown_log", "retired", "out_of_interval"):
            setattr(rep, name, getattr(rep, name) * tile)
        rep.per_log = {k: v * tile for k, v in rep.per_log.items()}
        for t in range(tile):
            for start, ana in analyses:
                if not ana.keep:
                    continue
                lis, eds = zip(*ana.keep)
                self.sink.store_raw_batch(RawBatch(
                    list(lis), list(eds),
                    start + t * total_span, log_url))
                rep.entries += len(ana.keep)
        self.sink.flush()
        st = dict(self.sink.verifier.stats)
        rep.verified = int(st.get("verified", 0))
        rep.failed = int(st.get("failed", 0))
        rep.verifier_no_sct = int(st.get("no_sct", 0))
        rep.verifier_no_key = int(st.get("no_key", 0))
        rep.device_lanes = int(st.get("device_lanes", 0))
        rep.host_lanes = int(st.get("host_lanes", 0))
        rep.per_issuer = self.aggregator.verify_counts()
        rep.wall_s = time.monotonic() - t0
        metrics.incr_counter("audit", "entries",
                             value=float(rep.entries))
        metrics.incr_counter("audit", "verified",
                             value=float(rep.verified))
        metrics.incr_counter("audit", "failed",
                             value=float(rep.failed))
        return rep

    def run_recorded(self, path_or_doc, tile: int = 1) -> AuditReport:
        doc = (path_or_doc if isinstance(path_or_doc, dict)
               else load_recorded(path_or_doc))
        pages = [(int(p.get("start", 0)), p["entries"])
                 for p in doc["pages"]]
        return self.run_pages(pages, log_url=doc.get("log_url",
                                                     "recorded-shard"),
                              tile=tile)

    def run_live(self, log_url: str, start: int, end: int,
                 transport=None, page_size: int = 256) -> AuditReport:
        """Fetch ``[start, end]`` through the production transport
        (retry/backoff/window-clamp included) and audit the pages as
        they arrive. ``transport`` is injectable for tests; the
        default is real HTTP."""
        from ct_mapreduce_tpu.ingest.ctclient import CTLogClient

        client = CTLogClient(log_url, transport=transport)

        def fetch():
            idx = start
            while idx <= end:
                got = client.get_raw_entries(
                    idx, min(end, idx + page_size - 1))
                if not got:
                    break
                yield idx, [{"leaf_input": e.leaf_input,
                             "extra_data": e.extra_data} for e in got]
                idx += len(got)

        return self.run_pages(fetch(), log_url=client.short_url)


def load_driver(log_list_path: Optional[str] = None,
                quarantine_dir: Optional[str] = None,
                **kwargs) -> AuditDriver:
    """Driver from resolved knobs: ``auditLogList`` names the log-list
    JSON (required — auditing without trust anchors is meaningless),
    ``auditQuarantineDir`` the spool (optional)."""
    from ct_mapreduce_tpu import audit as auditpkg

    path, qdir = auditpkg.resolve_audit(log_list_path, quarantine_dir)
    if not path:
        raise ValueError(
            "no log list configured: pass auditLogList / set "
            "CTMR_AUDIT_LOG_LIST (docs/AUDIT.md)")
    return AuditDriver(loglistlib.load_log_list(path),
                       quarantine_dir=qdir, **kwargs)
