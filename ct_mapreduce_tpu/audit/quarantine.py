"""Divergence quarantine lane (ROADMAP 5(a), closing the loop).

The standing differential harness (:mod:`ct_mapreduce_tpu.core.
divergence`) classifies native-vs-mirror disagreement; this module is
the lane that makes disagreement SAFE. Before an audit batch reaches
the verify lane, the native sidecar extractor
(:func:`ct_mapreduce_tpu.native.leafpack.extract_scts`) and the pure
Python mirror (:func:`ct_mapreduce_tpu.verify.sct.extract_scts_np`)
both run over the same rows; any lane where ANY extraction field
differs — the ok verdict, the RFC 6962 digest, log id, timestamp,
signature words, algorithm bytes — is:

1. excluded from the batch handed to the verifier/aggregator (the
   cert cannot alter aggregate counts in either direction), and
2. filed into a durable spool (``auditQuarantineDir``) as DER + a
   JSON sidecar naming the disagreeing fields, so the offending bytes
   survive for the differential harness to reduce.

The exclusion property is the contract: aggregate results must be
IDENTICAL whether the spool is replayed or dropped — quarantine is a
side-channel, never a third verdict. ``audit.quarantined`` counts
every filed lane.

When the native extractor is unavailable there is nothing to
disagree with: the mask is all-false and ``measured`` is False, so
callers can surface "divergence not measured" instead of a vacuous
zero.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ct_mapreduce_tpu.telemetry import metrics

SPOOL_FORMAT = "CTMRQR01"

# SctBatch surface compared lane-wise; a mismatch in any is divergence.
_FIELDS = ("ok", "digest", "log_id", "timestamp_ms", "r", "s",
           "hash_alg", "sig_alg")


@dataclass
class DivergenceCheck:
    """One batch's native-vs-mirror comparison."""

    mask: np.ndarray  # bool[n] — True = lane diverged
    reasons: dict[int, list[str]]  # lane -> disagreeing field names
    measured: bool  # False when the native extractor is absent

    @property
    def count(self) -> int:
        return int(self.mask.sum())


def compare_extractions(native, mirror) -> DivergenceCheck:
    """Lane-wise field diff of two :class:`~ct_mapreduce_tpu.verify.
    sct.SctBatch` extractions of the same rows."""
    n = native.ok.shape[0]
    mask = np.zeros((n,), bool)
    per_field: dict[str, np.ndarray] = {}
    for name in _FIELDS:
        a = np.asarray(getattr(native, name))
        b = np.asarray(getattr(mirror, name))
        diff = (a != b)
        if diff.ndim > 1:
            diff = diff.any(axis=tuple(range(1, diff.ndim)))
        per_field[name] = diff
        mask |= diff
    reasons = {
        int(i): [f for f in _FIELDS if per_field[f][i]]
        for i in np.flatnonzero(mask)
    }
    return DivergenceCheck(mask=mask, reasons=reasons, measured=True)


def check_batch(data: np.ndarray, length: np.ndarray,
                issuer_key_hash: Optional[np.ndarray] = None,
                ) -> DivergenceCheck:
    """Run both extractors over packed rows and diff them. ``data`` is
    uint8[n, pad], ``length`` int32[n], ``issuer_key_hash`` optional
    uint8[n, 32] (the per-lane RFC 6962 ikh both sides must agree
    under)."""
    from ct_mapreduce_tpu.verify import sct as sctlib

    n = data.shape[0]
    try:
        import os as _os

        from ct_mapreduce_tpu.native import leafpack
        from ct_mapreduce_tpu.native import load as load_native

        lib = (None if _os.environ.get("CTMR_NATIVE", "1") == "0"
               else load_native())
        native_ok = lib is not None and getattr(lib, "has_sct", False)
    except Exception:
        native_ok = False
    if not native_ok:
        return DivergenceCheck(mask=np.zeros((n,), bool), reasons={},
                               measured=False)
    native = leafpack.extract_scts(data, length,
                                   issuer_key_hash=issuer_key_hash)
    mirror = sctlib.extract_scts_np(data, length,
                                    issuer_key_hash=issuer_key_hash)
    return compare_extractions(native, mirror)


class QuarantineSpool:
    """Durable spool of diverged lanes.

    ``directory`` empty → in-memory only: lanes are still counted and
    excluded, nothing persists (the default posture when
    ``auditQuarantineDir`` is unset). With a directory, each lane is
    written tmp+rename as ``<sha256[:24]>.json`` carrying the DER
    (hex), its provenance, and the disagreeing fields; re-filing the
    same DER bytes overwrites the same name (the spool dedups by
    content, counts count filings)."""

    def __init__(self, directory: str = ""):
        self.directory = directory
        self.count = 0
        self.records: list[dict] = []
        if directory:
            os.makedirs(directory, exist_ok=True)

    def file(self, der: bytes, *, index: int = -1, log_url: str = "",
             reasons: Optional[list[str]] = None) -> dict:
        rec = {
            "format": SPOOL_FORMAT,
            "sha256": hashlib.sha256(der).hexdigest(),
            "index": index,
            "logUrl": log_url,
            "reasons": list(reasons or []),
            "der": der.hex(),
        }
        self.count += 1
        self.records.append(rec)
        metrics.incr_counter("audit", "quarantined")
        if self.directory:
            name = rec["sha256"][:24] + ".json"
            fd, tmp = tempfile.mkstemp(prefix=name + ".tmp.",
                                       dir=self.directory)
            try:
                with os.fdopen(fd, "w", encoding="utf-8") as fh:
                    json.dump(rec, fh, indent=1, sort_keys=True)
                    fh.write("\n")
                os.replace(tmp, os.path.join(self.directory, name))
            except BaseException:
                import contextlib
                with contextlib.suppress(OSError):
                    os.unlink(tmp)
                raise
        return rec

    def replay(self) -> list[dict]:
        """Load every spooled record from disk (or the in-memory list
        when no directory is configured) — the harness's feed and the
        exclusion-property test's evidence."""
        if not self.directory:
            return list(self.records)
        out = []
        for name in sorted(os.listdir(self.directory)):
            if not name.endswith(".json"):
                continue
            with open(os.path.join(self.directory, name),
                      encoding="utf-8") as fh:
                rec = json.load(fh)
            if rec.get("format") != SPOOL_FORMAT:
                raise ValueError(
                    f"unknown quarantine record format in {name}: "
                    f"{rec.get('format')!r}")
            out.append(rec)
        return out

    def replay_ders(self) -> list[bytes]:
        return [bytes.fromhex(r["der"]) for r in self.replay()]
