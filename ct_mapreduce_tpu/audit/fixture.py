"""Recorded-shard fixture builder: the deterministic ``CTMRAU01``
corpus checked in at ``tests/data/recorded_shard.json.gz``.

The zero-egress environment cannot capture a live shard, so the
fixture is SYNTHESIZED through the same wire encoders the transport
tests use (:mod:`ct_mapreduce_tpu.ingest.leaf`) and signed by
deterministic log keys published production-style: each signer's
``log_id`` is SHA-256 over its SPKI DER
(:func:`ct_mapreduce_tpu.audit.loglist.adopt_production_id`), and the
embedded log list is the Google/Apple v3 schema byte-for-byte in
shape. What the corpus models per page mix (the shape of a real
usable shard's entries):

- most lanes carry NO embedded SCT (precert-era entries and certs
  logged before issuance — the cheap majority);
- a P-256 ``usable`` temporally-sharded log signs the bulk of the
  verifiable SCTs (a few corrupted — real verify failures);
- a P-384 ``retired`` log's SCTs verify but are flagged;
- an RSA log exercises the host-fallback lane;
- a handful of SCTs cite a log absent from the list (``no_key``) or
  carry timestamps outside the signing shard's interval.

Regenerate with ``python -m ct_mapreduce_tpu.audit.fixture <out.gz>``
— output is byte-stable (sorted JSON, zeroed gzip mtime), so an
unchanged generator reproduces the checked-in bytes exactly.
"""

from __future__ import annotations

import datetime

from ct_mapreduce_tpu.audit import loglist as loglistlib
from ct_mapreduce_tpu.verify import host as vhost
from ct_mapreduce_tpu.verify import sct as sctlib

# One tile of the recorded shard. The mix keeps verifiable-SCT lanes
# a bounded minority so tier-1 scale runs stay inside the ECDSA
# budget (~1k host-side verifies/s on the CI box) while every lane
# class appears with enough mass to assert on.
PAGE_SIZE = 256
N_PAGES = 4
MIX = {
    "p256_valid": 120,
    "p256_corrupt": 16,
    "p384_retired": 24,
    "rsa": 16,
    "unknown_log": 16,
    "out_of_interval": 16,
    # remainder: no embedded SCT
}

INTERVAL = ("2024-01-01T00:00:00Z", "2025-01-01T00:00:00Z")
TS_IN_INTERVAL = 1_710_000_000_000  # 2024-03-09, inside
TS_OUTSIDE = 1_740_000_000_000  # 2025-02-19, past end_exclusive
N_ISSUERS = 8


def fixture_signers() -> dict:
    """The shard's log keys, production-id adopted (log_id =
    SHA-256(SPKI)). ``unknown`` signs real SCTs but is NOT in the
    published list."""
    return {
        "p256": loglistlib.adopt_production_id(
            sctlib.EcSctSigner("audit-shard:p256")),
        "p384": loglistlib.adopt_production_id(
            sctlib.EcSctSigner("audit-shard:p384", vhost.P384)),
        "rsa": loglistlib.adopt_production_id(sctlib.RsaSctSigner()),
        "unknown": loglistlib.adopt_production_id(
            sctlib.EcSctSigner("audit-shard:unlisted")),
    }


def fixture_log_list_doc(signers: dict) -> dict:
    return loglistlib.fixture_log_list([
        {"signer": signers["p256"], "operator": "Audit Fixture Op",
         "description": "audit shard 2024 (p256)",
         "url": "https://audit.ct.example/2024/",
         "interval": INTERVAL},
        {"signer": signers["p384"], "operator": "Audit Fixture Op",
         "description": "audit legacy (p384, retired)",
         "url": "https://audit.ct.example/legacy/",
         "state": "retired",
         "state_timestamp": "2025-06-01T00:00:00Z"},
        {"signer": signers["rsa"], "operator": "Second Fixture Op",
         "description": "audit rsa log",
         "url": "https://audit.ct.example/rsa/"},
    ])


def build_recorded_shard() -> dict:
    """The full CTMRAU01 document (pages + embedded log list)."""
    from ct_mapreduce_tpu.ingest import leaf as leaflib
    from ct_mapreduce_tpu.utils import minicert

    signers = fixture_signers()
    utc = datetime.timezone.utc
    future = datetime.datetime(2031, 6, 15, tzinfo=utc)
    issuers = [
        minicert.make_cert(
            serial=100 + i, issuer_cn=f"Audit Real CA {i:02d}",
            org=f"Audit Org {i % 3}", is_ca=True, not_after=future)
        for i in range(N_ISSUERS)
    ]

    n = PAGE_SIZE * N_PAGES
    kinds = (["p256_valid"] * MIX["p256_valid"]
             + ["p256_corrupt"] * MIX["p256_corrupt"]
             + ["p384_retired"] * MIX["p384_retired"]
             + ["rsa"] * MIX["rsa"]
             + ["unknown_log"] * MIX["unknown_log"]
             + ["out_of_interval"] * MIX["out_of_interval"])
    kinds += ["no_sct"] * (n - len(kinds))
    # Deterministic interleave (no RNG: stride through the classes) so
    # every page carries every lane class.
    stride = 67  # coprime with 1024 — a full permutation
    order = [(i * stride) % n for i in range(n)]
    placed = [kinds[order.index(i)] for i in range(n)]

    import base64

    pages = []
    for p in range(N_PAGES):
        entries = []
        for j in range(PAGE_SIZE):
            idx = p * PAGE_SIZE + j
            kind = placed[idx]
            issuer = issuers[idx % N_ISSUERS]
            base = minicert.make_cert(
                serial=10_000 + idx,
                issuer_cn=f"Audit Real CA {idx % N_ISSUERS:02d}",
                org=f"Audit Org {(idx % N_ISSUERS) % 3}",
                subject_cn=f"entry-{idx}.audit.example", is_ca=False,
                not_after=future)
            ts = TS_IN_INTERVAL + idx
            if kind == "no_sct":
                der = base
            else:
                signer = {
                    "p256_valid": signers["p256"],
                    "p256_corrupt": signers["p256"],
                    "out_of_interval": signers["p256"],
                    "p384_retired": signers["p384"],
                    "rsa": signers["rsa"],
                    "unknown_log": signers["unknown"],
                }[kind]
                if kind == "out_of_interval":
                    ts = TS_OUTSIDE + idx
                der = sctlib.attach_sct(
                    base, signer, ts,
                    corrupt_signature=(kind == "p256_corrupt"),
                    issuer_der=issuer)
            li = leaflib.encode_leaf_input(
                der, timestamp_ms=ts)
            ed = leaflib.encode_extra_data([issuer])
            entries.append({
                "leaf_input": base64.b64encode(li).decode(),
                "extra_data": base64.b64encode(ed).decode(),
            })
        pages.append({"start": p * PAGE_SIZE, "entries": entries})

    return {
        "log_url": "https://audit.ct.example/2024/",
        "description": "synthesized recorded shard (audit fixture)",
        "mix": dict(MIX, no_sct=n - sum(MIX.values())),
        "log_list": fixture_log_list_doc(signers),
        "pages": pages,
    }


def expected_tallies() -> dict:
    """Ground truth per tile, derived from MIX — the oracle the audit
    gate recomputes against."""
    n = PAGE_SIZE * N_PAGES
    sct = sum(MIX.values())
    return {
        "entries": n,
        "sct_lanes": sct,
        "no_sct": n - sct,
        # out_of_interval lanes still verify (the key is right; the
        # routing flag is policy, not cryptography).
        "verified": (MIX["p256_valid"] + MIX["p384_retired"]
                     + MIX["rsa"] + MIX["out_of_interval"]),
        "failed": MIX["p256_corrupt"],
        "no_key": MIX["unknown_log"],
        "device_lanes": (MIX["p256_valid"] + MIX["p256_corrupt"]
                         + MIX["p384_retired"]
                         + MIX["out_of_interval"]),
        "host_lanes": MIX["rsa"],
        "retired": MIX["p384_retired"],
        "out_of_interval": MIX["out_of_interval"],
        "unknown_log": MIX["unknown_log"],
    }


def shard_ders(doc: dict) -> list:
    """Every entry's stored cert DER, decoded through the production
    leaf codec — the real-corpus feed for the differential harness."""
    from ct_mapreduce_tpu.ingest import leaf as leaflib

    ders = []
    for page in doc["pages"]:
        start = int(page.get("start", 0))
        for i, e in enumerate(page["entries"]):
            ders.append(leaflib.decode_json_entry(start + i, e).cert_der)
    return ders


def record_divergence_trend(
        shard_path: str = "tests/data/recorded_shard.json.gz",
        trend_path: str = "DIVERGENCE_TREND.json") -> dict:
    """Classify the recorded shard through the parser differential
    harness and append a ``real``-corpus run to the trend file (the
    first such run pins ``floorRealAcceptRate`` — the tier-1 gate in
    tests/test_der_kernel.py grades fresh runs against it)."""
    from ct_mapreduce_tpu.audit import driver as drvlib
    from ct_mapreduce_tpu.core import divergence

    doc = drvlib.load_recorded(shard_path)
    report = divergence.classify_corpus(shard_ders(doc))
    return divergence.record_trend(report, trend_path, corpus="real")


def main(argv=None) -> int:
    import sys

    from ct_mapreduce_tpu.audit import driver as drvlib

    args = list(sys.argv[1:] if argv is None else argv)
    if args and args[0] == "--trend":
        doc = record_divergence_trend(*args[1:3])
        run = doc["runs"][-1]
        print(f"recorded real-corpus run {run['run']}: accept rate "
              f"{run['deviceAcceptRate']} (floor "
              f"{doc.get('floorRealAcceptRate')})")
        return 0
    out = args[0] if args else "tests/data/recorded_shard.json.gz"
    doc = build_recorded_shard()
    drvlib.write_recorded(out, doc)
    n = sum(len(p["entries"]) for p in doc["pages"])
    print(f"wrote {out}: {len(doc['pages'])} pages, {n} entries")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
