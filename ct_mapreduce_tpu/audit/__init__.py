"""Real-log audit subsystem (round 24, ROADMAP item 2).

The reference's whole job is fetching and checking *real* CT logs;
this package turns the reproduction into an auditor:

- :mod:`~ct_mapreduce_tpu.audit.loglist` — the production
  Google/Apple log-list v3 JSON schema loaded into the verify lane's
  :class:`~ct_mapreduce_tpu.verify.lane.LogKeyRegistry`
  (log_id = SHA-256(SPKI) enforced loudly, operator + state +
  temporal-shard intervals carried per entry) with temporal-shard
  routing: an SCT is checked against the shard that was accepting at
  its timestamp.
- :mod:`~ct_mapreduce_tpu.audit.quarantine` — the durable quarantine
  spool (ROADMAP 5(a)): any lane where the native extractor and the
  python mirror disagree on parse or verdict inputs routes here
  instead of the aggregate, so a divergent cert can never silently
  alter counts.
- :mod:`~ct_mapreduce_tpu.audit.driver` — the recorded-shard audit
  pipeline: real-wire ``get-entries`` pages (checked-in compressed
  fixture, or ``--live`` over the existing transport) through
  decode → RFC 6962 TBS-reconstructed verify → aggregate → filter,
  per-issuer verified/failed counts into statistics/serve/checkpoints.

Knobs ride the platformProfile ladder as the ``knobs.audit`` section
(explicit directive > ``CTMR_*`` env > profile > default), consistent
with every other subsystem since round 18.
"""

from __future__ import annotations

from typing import Optional

from ct_mapreduce_tpu.config import profile as platprofile

_AUDIT_KNOBS = (
    # Identity/policy knobs — never swept (tune/registry.py EXCLUDED).
    platprofile.Knob("auditLogList", "CTMR_AUDIT_LOG_LIST", "",
                     parse=str, is_set=platprofile.nonempty_str),
    platprofile.Knob("auditQuarantineDir", "CTMR_AUDIT_QUARANTINE_DIR",
                     "", parse=str, is_set=platprofile.nonempty_str),
)


def resolve_audit(log_list: Optional[str] = None,
                  quarantine_dir: Optional[str] = None,
                  ) -> tuple[str, str]:
    """Resolve the audit knobs through the shared platformProfile
    ladder: explicit value (config directive / kwarg) >
    ``CTMR_AUDIT_LOG_LIST`` / ``CTMR_AUDIT_QUARANTINE_DIR`` env >
    profile ``knobs.audit`` > defaults (no pinned log list; no
    durable quarantine spool — divergent lanes are still excluded
    from aggregates, just not persisted)."""
    r = platprofile.resolve_section("audit", _AUDIT_KNOBS, {
        "auditLogList": log_list or "",
        "auditQuarantineDir": quarantine_dir or "",
    })
    return r["auditLogList"], r["auditQuarantineDir"]
