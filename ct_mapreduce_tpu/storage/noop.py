"""Null-object StorageBackend: stores succeed silently, loads fail.

Reference: /root/reference/storage/noopbackend.go (the default when no
certPath is configured — cache-only operation,
/root/reference/engine/engine.go:36-40).
"""

from __future__ import annotations

from datetime import datetime
from typing import Iterator, Optional

from ct_mapreduce_tpu.core.types import (
    CertificateLog,
    ExpDate,
    Issuer,
    Serial,
    UniqueCertIdentifier,
)
from ct_mapreduce_tpu.storage.interfaces import StorageBackend


class NoopBackend(StorageBackend):
    def mark_dirty(self, id_: str) -> None:
        pass

    def store_certificate_pem(self, serial, exp_date, issuer, pem) -> None:
        pass

    def store_log_state(self, log: CertificateLog) -> None:
        pass

    def store_known_certificate_list(self, issuer, serials) -> None:
        pass

    def load_certificate_pem(self, serial, exp_date, issuer) -> bytes:
        raise NotImplementedError("NoopBackend does not store certificates")

    def load_log_state(self, log_url: str) -> Optional[CertificateLog]:
        return None

    def allocate_exp_date_and_issuer(self, exp_date, issuer) -> None:
        pass

    def list_expiration_dates(self, not_before: datetime) -> list[ExpDate]:
        return []

    def list_issuers_for_expiration_date(self, exp_date: ExpDate) -> list[Issuer]:
        return []

    def list_serials_for_expiration_date_and_issuer(
        self, exp_date: ExpDate, issuer: Issuer
    ) -> list[Serial]:
        return []

    def stream_serials_for_expiration_date_and_issuer(
        self, exp_date: ExpDate, issuer: Issuer
    ) -> Iterator[UniqueCertIdentifier]:
        return iter(())
