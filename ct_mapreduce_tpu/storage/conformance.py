"""Shared StorageBackend conformance suite.

Reference: /root/reference/storage/storagebackend_tests.go — the same
assertions run against every backend implementation (store/load,
listing, log state, hour-resolution listing). Call these from a test
module with any backend instance; they raise AssertionError on
contract violations.
"""

from __future__ import annotations

from datetime import datetime, timezone

from ct_mapreduce_tpu.core.types import CertificateLog, ExpDate, Issuer, Serial
from ct_mapreduce_tpu.storage.interfaces import StorageBackend


def backend_test_store_load(backend: StorageBackend) -> None:
    """storagebackend_tests.go:39-53."""
    exp = ExpDate.parse("2050-01-01")
    issuer = Issuer.from_string("aki")
    serial = Serial.from_hex("01020304")
    pem = b"-----BEGIN CERTIFICATE-----\nZm9v\n-----END CERTIFICATE-----\n"
    backend.store_certificate_pem(serial, exp, issuer, pem)
    loaded = backend.load_certificate_pem(serial, exp, issuer)
    assert loaded == pem, f"load mismatch: {loaded!r} != {pem!r}"


def backend_test_log_state(backend: StorageBackend) -> None:
    """storagebackend_tests.go:103-169."""
    assert backend.load_log_state("not/a/log") is None
    log = CertificateLog(
        short_url="log.example.com/2050",
        max_entry=42,
        last_entry_time=datetime(2049, 1, 2, 3, 4, 5, tzinfo=timezone.utc),
    )
    backend.store_log_state(log)
    restored = backend.load_log_state("log.example.com/2050")
    assert restored is not None
    assert restored.short_url == log.short_url
    assert restored.max_entry == 42
    assert restored.last_entry_time == log.last_entry_time
    # Overwrite advances
    log.max_entry = 99
    backend.store_log_state(log)
    assert backend.load_log_state("log.example.com/2050").max_entry == 99


def backend_test_listing(backend: StorageBackend) -> None:
    """storagebackend_tests.go:55-101,171-215: allocation + listing with
    day and hour resolution."""
    day = ExpDate.parse("2051-03-04")
    hour = ExpDate.parse("2051-03-04-05")
    iss_a = Issuer.from_string("issuerA")
    iss_b = Issuer.from_string("issuerB")
    backend.allocate_exp_date_and_issuer(day, iss_a)
    backend.allocate_exp_date_and_issuer(hour, iss_b)

    not_before = datetime(2051, 1, 1, tzinfo=timezone.utc)
    dates = backend.list_expiration_dates(not_before)
    ids = {d.id() for d in dates}
    assert "2051-03-04" in ids and "2051-03-04-05" in ids, ids

    # Expired buckets are filtered out
    later = datetime(2052, 1, 1, tzinfo=timezone.utc)
    assert all(
        not d.id().startswith("2051-03-04")
        for d in backend.list_expiration_dates(later)
    )

    issuers_day = {i.id() for i in backend.list_issuers_for_expiration_date(day)}
    assert issuers_day == {"issuerA"}
    issuers_hour = {i.id() for i in backend.list_issuers_for_expiration_date(hour)}
    assert issuers_hour == {"issuerB"}


def backend_test_serials(backend: StorageBackend) -> None:
    """Serial listing and streaming (implemented here even though the
    reference's localdisk leaves streaming unimplemented,
    localdiskbackend.go:172-182)."""
    exp = ExpDate.parse("2053-06-07")
    issuer = Issuer.from_string("serialIssuer")
    serials = [Serial.from_hex(h) for h in ("00aa", "01", "02ff")]
    for s in serials:
        backend.store_certificate_pem(s, exp, issuer, b"PEM" + s.binary_string())
    listed = backend.list_serials_for_expiration_date_and_issuer(exp, issuer)
    assert sorted(x.hex_string() for x in listed) == ["00aa", "01", "02ff"]
    streamed = list(
        backend.stream_serials_for_expiration_date_and_issuer(exp, issuer)
    )
    assert len(streamed) == 3
    for uci in streamed:
        assert uci.exp_date.id() == exp.id()
        assert uci.issuer.id() == issuer.id()


def run_full_conformance(backend: StorageBackend) -> None:
    backend_test_store_load(backend)
    backend_test_log_state(backend)
    backend_test_listing(backend)
    backend_test_serials(backend)
