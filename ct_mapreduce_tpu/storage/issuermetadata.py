"""Per-issuer metadata accumulation: CRL distribution points and issuer
DNs, with local known-maps to skip cache round trips.

Reference: /root/reference/storage/issuermetadata.go. Keys
`crl::<issuerID>` and `issuer::<issuerID>`; CRL URLs are filtered to
http/https (ldap/ldaps silently dropped, unknown schemes ignored,
issuermetadata.go:48-73); `accumulate` returns whether this issuer had
already been seen with this expiration bucket — the caller uses that to
trigger directory allocation (filesystemdatabase.go:185-195).
"""

from __future__ import annotations

import threading
from typing import Iterable
from urllib.parse import urlparse

from ct_mapreduce_tpu.core.types import ExpDate, Issuer
from ct_mapreduce_tpu.storage.interfaces import RemoteCache

CRL_PREFIX = "crl"
ISSUERS_PREFIX = "issuer"


def crl_key(issuer: Issuer) -> str:
    return f"{CRL_PREFIX}::{issuer.id()}"


def issuers_key(issuer: Issuer) -> str:
    return f"{ISSUERS_PREFIX}::{issuer.id()}"


class IssuerMetadata:
    def __init__(self, issuer: Issuer, cache: RemoteCache):
        self.issuer = issuer
        self.cache = cache
        self._lock = threading.RLock()
        self._known_crl_dps: set[str] = set()
        self._known_issuer_dns: set[str] = set()
        self._known_exp_dates: set[str] = set()

    def id(self) -> str:
        return self.issuer.id()

    def _add_crl(self, crl: str) -> None:
        try:
            url = urlparse(crl.strip())
        except ValueError:
            return
        if url.scheme in ("ldap", "ldaps"):
            return
        if url.scheme not in ("http", "https"):
            return
        self.cache.set_insert(crl_key(self.issuer), url.geturl())

    def _add_issuer_dn(self, dn: str) -> None:
        self.cache.set_insert(issuers_key(self.issuer), dn)

    def accumulate(
        self, exp_date: ExpDate, issuer_dn: str, crl_dps: Iterable[str]
    ) -> bool:
        """Accumulate one certificate's metadata; must tolerate
        duplicates. Returns seen_exp_date_before
        (issuermetadata.go:92-138). Takes the already-extracted fields
        (the TPU pipeline extracts them in batch) rather than a parsed
        cert object."""
        exp_id = exp_date.id()
        with self._lock:
            seen_exp_date_before = exp_id in self._known_exp_dates
            seen_issuer_dn = issuer_dn in self._known_issuer_dns
            if not seen_exp_date_before:
                self._known_exp_dates.add(exp_id)
            new_dps = [dp for dp in crl_dps if dp not in self._known_crl_dps]
            self._known_crl_dps.update(new_dps)
            if not seen_issuer_dn:
                self._known_issuer_dns.add(issuer_dn)

        for dp in new_dps:
            self._add_crl(dp)
        if not seen_issuer_dn:
            self._add_issuer_dn(issuer_dn)
        return seen_exp_date_before

    def issuers(self) -> list[str]:
        return self.cache.set_list(issuers_key(self.issuer))

    def crls(self) -> list[str]:
        return self.cache.set_list(crl_key(self.issuer))
