"""In-memory RemoteCache for tests and single-process runs.

Parity with the reference's MockRemoteCache
(/root/reference/storage/mockcache.go): sorted-set semantics, lazily
honored TTLs, and a `duplicate` fidelity knob that replays members to
simulate Redis SSCAN duplication (mockcache.go:14-36,109-118) — the
behavior the client-side dedup in KnownCertificates.Known() exists to
absorb. Unlike the reference's mock, queues are implemented (the
reference leaves them unimplemented, mockcache.go:140-150; the real
RedisCache has them, so ours does too for coordinator tests).
"""

from __future__ import annotations

import fnmatch
import threading
import time
from bisect import bisect_left, insort
from datetime import datetime, timedelta, timezone
from typing import Iterator, Optional

from ct_mapreduce_tpu.core.types import CertificateLog
from ct_mapreduce_tpu.storage.interfaces import RemoteCache


class MockRemoteCache(RemoteCache):
    def __init__(self, duplicate: int = 0):
        # duplicate: replay each streamed member this many extra times
        self.duplicate = duplicate
        self._sets: dict[str, list[str]] = {}
        self._lists: dict[str, list[str]] = {}
        self._kv: dict[str, str] = {}
        self._expirations: dict[str, datetime] = {}
        self._lock = threading.RLock()

    # -- expiry ----------------------------------------------------------
    def _now(self) -> datetime:
        return datetime.now(timezone.utc)

    def cleanup_expiry(self) -> None:
        """Lazily drop expired keys (mockcache.go:28-36)."""
        with self._lock:
            now = self._now()
            expired = [k for k, t in self._expirations.items() if t <= now]
            for k in expired:
                self._sets.pop(k, None)
                self._lists.pop(k, None)
                self._kv.pop(k, None)
                del self._expirations[k]

    # -- sets ------------------------------------------------------------
    def exists(self, key: str) -> bool:
        self.cleanup_expiry()
        with self._lock:
            return key in self._sets or key in self._lists or key in self._kv

    def set_insert(self, key: str, entry: str) -> bool:
        self.cleanup_expiry()
        with self._lock:
            members = self._sets.setdefault(key, [])
            idx = bisect_left(members, entry)
            if idx < len(members) and members[idx] == entry:
                return False
            members.insert(idx, entry)
            return True

    def set_remove(self, key: str, entry: str) -> bool:
        self.cleanup_expiry()
        with self._lock:
            members = self._sets.get(key, [])
            idx = bisect_left(members, entry)
            if idx < len(members) and members[idx] == entry:
                members.pop(idx)
                return True
            return False

    def set_contains(self, key: str, entry: str) -> bool:
        self.cleanup_expiry()
        with self._lock:
            members = self._sets.get(key, [])
            idx = bisect_left(members, entry)
            return idx < len(members) and members[idx] == entry

    def set_list(self, key: str) -> list[str]:
        self.cleanup_expiry()
        with self._lock:
            return list(self._sets.get(key, []))

    def set_to_iter(self, key: str) -> Iterator[str]:
        self.cleanup_expiry()
        with self._lock:
            members = list(self._sets.get(key, []))
        for m in members:
            yield m
            for _ in range(self.duplicate):
                yield m

    def set_cardinality(self, key: str) -> int:
        self.cleanup_expiry()
        with self._lock:
            return len(self._sets.get(key, []))

    # -- TTLs ------------------------------------------------------------
    def expire_at(self, key: str, exp_time: datetime) -> None:
        if exp_time.tzinfo is None:
            exp_time = exp_time.replace(tzinfo=timezone.utc)
        with self._lock:
            self._expirations[key] = exp_time

    def expire_in(self, key: str, duration: timedelta) -> None:
        with self._lock:
            self._expirations[key] = self._now() + duration

    # -- queues ----------------------------------------------------------
    def queue(self, key: str, identifier: str) -> int:
        self.cleanup_expiry()
        with self._lock:
            lst = self._lists.setdefault(key, [])
            lst.append(identifier)
            return len(lst)

    def pop(self, key: str) -> str:
        self.cleanup_expiry()
        with self._lock:
            lst = self._lists.get(key)
            if not lst:
                raise KeyError(key)
            return lst.pop(0)

    def queue_length(self, key: str) -> int:
        self.cleanup_expiry()
        with self._lock:
            return len(self._lists.get(key, []))

    def blocking_pop_copy(self, key: str, dest: str, timeout: timedelta) -> str:
        deadline = time.monotonic() + timeout.total_seconds()
        while True:
            with self._lock:
                lst = self._lists.get(key)
                if lst:
                    value = lst.pop()  # BRPOPLPUSH pops from the tail
                    self._lists.setdefault(dest, []).insert(0, value)
                    return value
            if time.monotonic() >= deadline:
                raise TimeoutError(key)
            time.sleep(0.005)

    def list_remove(self, key: str, value: str) -> None:
        with self._lock:
            lst = self._lists.get(key, [])
            self._lists[key] = [v for v in lst if v != value]

    # -- SETNX / scan / log state ---------------------------------------
    def try_set(self, key: str, value: str, life: timedelta) -> str:
        self.cleanup_expiry()
        with self._lock:
            if key in self._kv:
                return self._kv[key]
            self._kv[key] = value
            self._expirations[key] = self._now() + life
            return value

    def put(self, key: str, value: str,
            life: Optional[timedelta] = None) -> None:
        self.cleanup_expiry()
        with self._lock:
            self._kv[key] = value
            if life is None:
                self._expirations.pop(key, None)
            else:
                self._expirations[key] = self._now() + life

    def get(self, key: str) -> Optional[str]:
        self.cleanup_expiry()
        with self._lock:
            return self._kv.get(key)

    def keys_matching(self, pattern: str) -> Iterator[str]:
        self.cleanup_expiry()
        with self._lock:
            keys = list(self._sets) + list(self._lists) + list(self._kv)
        for k in keys:
            if fnmatch.fnmatchcase(k, pattern):
                yield k

    def store_log_state(self, log: CertificateLog) -> None:
        with self._lock:
            self._kv[f"log::{log.short_url}"] = log.to_json()

    def load_log_state(self, short_url: str) -> Optional[CertificateLog]:
        with self._lock:
            raw = self._kv.get(f"log::{short_url}")
        return CertificateLog.from_json(raw) if raw else None
