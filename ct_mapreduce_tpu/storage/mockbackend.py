"""In-memory StorageBackend for tests.

Reference: /root/reference/storage/mockbackend.go — maps for
expDate→issuers, (expDate, issuer)→serials, and a byte store.
"""

from __future__ import annotations

from datetime import datetime, timezone
from typing import Iterator, Optional

from ct_mapreduce_tpu.core.types import (
    CertificateLog,
    ExpDate,
    Issuer,
    Serial,
    UniqueCertIdentifier,
)
from ct_mapreduce_tpu.storage.interfaces import StorageBackend


class MockBackend(StorageBackend):
    def __init__(self):
        self.dirty: set[str] = set()
        self.exp_dates: dict[str, ExpDate] = {}
        self.issuers: dict[str, set[str]] = {}  # expDate id -> issuer ids
        self.serials: dict[tuple[str, str], dict[str, Serial]] = {}
        self.pems: dict[tuple[str, str, str], bytes] = {}
        self.log_states: dict[str, str] = {}
        self.known_lists: dict[str, list[Serial]] = {}

    def mark_dirty(self, id_: str) -> None:
        self.dirty.add(id_)

    def store_certificate_pem(
        self, serial: Serial, exp_date: ExpDate, issuer: Issuer, pem: bytes
    ) -> None:
        self.allocate_exp_date_and_issuer(exp_date, issuer)
        self.serials.setdefault((exp_date.id(), issuer.id()), {})[serial.id()] = serial
        self.pems[(exp_date.id(), issuer.id(), serial.id())] = bytes(pem)

    def store_log_state(self, log: CertificateLog) -> None:
        self.log_states[log.short_url] = log.to_json()

    def store_known_certificate_list(
        self, issuer: Issuer, serials: list[Serial]
    ) -> None:
        self.known_lists[issuer.id()] = list(serials)

    def load_certificate_pem(
        self, serial: Serial, exp_date: ExpDate, issuer: Issuer
    ) -> bytes:
        try:
            return self.pems[(exp_date.id(), issuer.id(), serial.id())]
        except KeyError as exc:
            raise FileNotFoundError(str(exc)) from exc

    def load_log_state(self, short_url: str) -> Optional[CertificateLog]:
        raw = self.log_states.get(short_url)
        return CertificateLog.from_json(raw) if raw else None

    def allocate_exp_date_and_issuer(self, exp_date: ExpDate, issuer: Issuer) -> None:
        self.exp_dates[exp_date.id()] = exp_date
        self.issuers.setdefault(exp_date.id(), set()).add(issuer.id())
        self.serials.setdefault((exp_date.id(), issuer.id()), {})

    def list_expiration_dates(self, not_before: datetime) -> list[ExpDate]:
        if not_before.tzinfo is None:
            not_before = not_before.replace(tzinfo=timezone.utc)
        # Midnight truncation keeps same-day hour buckets (localdiskbackend.go:98)
        not_before = not_before.replace(hour=0, minute=0, second=0, microsecond=0)
        return sorted(
            (e for e in self.exp_dates.values() if not e.is_expired_at(not_before)),
        )

    def list_issuers_for_expiration_date(self, exp_date: ExpDate) -> list[Issuer]:
        return [
            Issuer.from_string(i) for i in sorted(self.issuers.get(exp_date.id(), ()))
        ]

    def list_serials_for_expiration_date_and_issuer(
        self, exp_date: ExpDate, issuer: Issuer
    ) -> list[Serial]:
        return sorted(self.serials.get((exp_date.id(), issuer.id()), {}).values())

    def stream_serials_for_expiration_date_and_issuer(
        self, exp_date: ExpDate, issuer: Issuer
    ) -> Iterator[UniqueCertIdentifier]:
        for serial in self.list_serials_for_expiration_date_and_issuer(
            exp_date, issuer
        ):
            yield UniqueCertIdentifier(exp_date=exp_date, issuer=issuer, serial=serial)
