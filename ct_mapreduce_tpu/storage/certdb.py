"""FilesystemDatabase: the host-path CertDatabase implementation.

Reference: /root/reference/storage/filesystemdatabase.go — per-cert
`store` orchestrates dedup → metadata accumulation → directory
allocation → PEM store → dirty-mark (:158-211); log state is
dual-written to cache and backend with cache-first reads (:110-139);
KnownCertificates handles are cached (8,192-entry ARC, :32 — here an
LRU); GetIssuerAndDatesFromCache enumerates `serials::*` keys
(:59-100).

This host path is the behavioral baseline the TPU pipeline is checked
against ("issuer-count parity"); the batched device path lives in
ct_mapreduce_tpu.agg.aggregator.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from datetime import datetime
from typing import Optional

from ct_mapreduce_tpu.core import der as derlib
from ct_mapreduce_tpu.core.types import (
    CertificateLog,
    ExpDate,
    Issuer,
    IssuerDate,
    Serial,
)
from ct_mapreduce_tpu.storage.interfaces import (
    CertDatabase,
    RemoteCache,
    StorageBackend,
)
from ct_mapreduce_tpu.storage.issuermetadata import IssuerMetadata
from ct_mapreduce_tpu.storage.knowncerts import SERIALS_PREFIX, KnownCertificates
from ct_mapreduce_tpu.telemetry import metrics

KNOWN_CERTS_CACHE_SIZE = 8192  # filesystemdatabase.go:32


class _LRU:
    def __init__(self, capacity: int):
        self.capacity = capacity
        self._data: OrderedDict[str, KnownCertificates] = OrderedDict()
        self._lock = threading.Lock()

    def get_or_create(self, key: str, factory) -> KnownCertificates:
        with self._lock:
            if key in self._data:
                self._data.move_to_end(key)
                return self._data[key]
            value = factory()
            self._data[key] = value
            if len(self._data) > self.capacity:
                self._data.popitem(last=False)
            return value


class FilesystemDatabase(CertDatabase):
    def __init__(self, backend: StorageBackend, ext_cache: RemoteCache):
        self.backend = backend
        self.ext_cache = ext_cache
        self._known_certs = _LRU(KNOWN_CERTS_CACHE_SIZE)
        self._issuer_metadata: dict[str, IssuerMetadata] = {}
        self._meta_lock = threading.RLock()
        # Distinct issuer certs are few; memoize DER -> Issuer so the
        # per-entry hot path doesn't re-walk the issuer TLV tree.
        self._issuer_by_der: dict[bytes, Issuer] = {}

    # -- log state ------------------------------------------------------
    def save_log_state(self, log: CertificateLog) -> None:
        # Dual write: cache + backend (filesystemdatabase.go:110-118)
        self.ext_cache.store_log_state(log)
        self.backend.store_log_state(log)

    def get_log_state(self, short_url: str) -> CertificateLog:
        # Cache first, backend fallback (filesystemdatabase.go:120-139)
        log = self.ext_cache.load_log_state(short_url)
        if log is None:
            log = self.backend.load_log_state(short_url)
        if log is None:
            log = CertificateLog(short_url=short_url)
        return log

    # -- the per-cert map+reduce ---------------------------------------
    def store(
        self, cert_der: bytes, issuer_der: bytes, log_url: str, entry_id: int
    ) -> None:
        with metrics.measure("FilesystemDatabase", "Store"):
            fields = derlib.parse_cert(cert_der)
            issuer = self._issuer_by_der.get(issuer_der)
            if issuer is None:
                issuer = Issuer.from_spki(derlib.parse_cert(issuer_der).spki)
                self._issuer_by_der[issuer_der] = issuer
            self.store_parsed(
                serial=Serial(fields.serial),
                exp_date=ExpDate.from_time(fields.not_after),
                issuer=issuer,
                issuer_dn=fields.issuer_dn,
                crl_dps=fields.crl_distribution_points,
                cert_der=cert_der,
            )

    def store_parsed(
        self,
        serial: Serial,
        exp_date: ExpDate,
        issuer: Issuer,
        issuer_dn: str,
        crl_dps: list[str],
        cert_der: Optional[bytes] = None,
    ) -> None:
        """The reduce step on already-extracted fields — the same
        sequencing as filesystemdatabase.go:158-211, callable directly
        by the batched pipeline's drain."""
        known_certs = self.get_known_certificates(exp_date, issuer)
        if known_certs.was_unknown(serial):
            meta = self.get_issuer_metadata(issuer)
            seen_exp_date_before = meta.accumulate(exp_date, issuer_dn, crl_dps)
            if not seen_exp_date_before:
                self.backend.allocate_exp_date_and_issuer(exp_date, issuer)
            if cert_der is not None:
                self.backend.store_certificate_pem(
                    serial, exp_date, issuer, derlib.der_to_pem(cert_der)
                )
            metrics.incr_counter("FilesystemDatabase", "StoreUnknown")
        # Dirty-mark the expiry day (filesystemdatabase.go:141-144,204-208)
        self.backend.mark_dirty(exp_date.date.strftime("%Y-%m-%d"))

    # -- handles --------------------------------------------------------
    def get_known_certificates(
        self, exp_date: ExpDate, issuer: Issuer
    ) -> KnownCertificates:
        key = f"{exp_date.id()}::{issuer.id()}"
        return self._known_certs.get_or_create(
            key, lambda: KnownCertificates(exp_date, issuer, self.ext_cache)
        )

    def get_issuer_metadata(self, issuer: Issuer) -> IssuerMetadata:
        with self._meta_lock:
            meta = self._issuer_metadata.get(issuer.id())
            if meta is None:
                meta = IssuerMetadata(issuer, self.ext_cache)
                self._issuer_metadata[issuer.id()] = meta
            return meta

    # -- enumeration ----------------------------------------------------
    def get_issuer_and_dates_from_cache(self) -> list[IssuerDate]:
        # Scan serials::<exp>::<issuer> keys (filesystemdatabase.go:59-100)
        grouped: dict[str, list[ExpDate]] = {}
        for key in self.ext_cache.keys_matching(f"{SERIALS_PREFIX}::*"):
            parts = key.split("::")
            if len(parts) != 3:
                continue
            try:
                exp = ExpDate.parse(parts[1])
            except ValueError:
                continue
            grouped.setdefault(parts[2], []).append(exp)
        return [
            IssuerDate(issuer=Issuer.from_string(issuer_id), exp_dates=sorted(dates))
            for issuer_id, dates in sorted(grouped.items())
        ]

    def list_expiration_dates(self, not_before: datetime) -> list[ExpDate]:
        return self.backend.list_expiration_dates(not_before)

    def list_issuers_for_expiration_date(self, exp_date: ExpDate) -> list[Issuer]:
        return self.backend.list_issuers_for_expiration_date(exp_date)

    def cleanup(self) -> None:
        pass
