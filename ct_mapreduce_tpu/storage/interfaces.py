"""Storage-layer interfaces.

Direct analogs of the reference's three seams
(/root/reference/storage/types.go:46-102): `StorageBackend` (durable
PEM/log-state storage), `RemoteCache` (shared-state fabric: sets,
queues, TTLs, SETNX, key scan), and `CertDatabase` (the facade the
ingest engine calls per certificate).
"""

from __future__ import annotations

import abc
from datetime import datetime, timedelta
from typing import Callable, Iterable, Iterator, Optional

from ct_mapreduce_tpu.core.types import (
    CertificateLog,
    ExpDate,
    Issuer,
    IssuerDate,
    Serial,
    UniqueCertIdentifier,
)


class RemoteCache(abc.ABC):
    """Shared mutable state fabric. Reference: storage/types.go:83-102.

    Set members and values are `str` (binary-safe via latin-1 where
    callers store raw serial bytes, matching Go's string-as-bytes).
    """

    @abc.abstractmethod
    def exists(self, key: str) -> bool: ...

    @abc.abstractmethod
    def set_insert(self, key: str, entry: str) -> bool:
        """Insert into a set; True iff the entry was newly added."""

    @abc.abstractmethod
    def set_remove(self, key: str, entry: str) -> bool: ...

    @abc.abstractmethod
    def set_contains(self, key: str, entry: str) -> bool: ...

    @abc.abstractmethod
    def set_list(self, key: str) -> list[str]: ...

    @abc.abstractmethod
    def set_to_iter(self, key: str) -> Iterator[str]:
        """Stream set members; may yield duplicates (Redis SSCAN
        semantics — the reference documents and tolerates this,
        storage/knowncertificates.go:66-68)."""

    @abc.abstractmethod
    def set_cardinality(self, key: str) -> int: ...

    @abc.abstractmethod
    def expire_at(self, key: str, exp_time: datetime) -> None: ...

    @abc.abstractmethod
    def expire_in(self, key: str, duration: timedelta) -> None: ...

    @abc.abstractmethod
    def queue(self, key: str, identifier: str) -> int:
        """RPUSH; returns resulting queue length."""

    @abc.abstractmethod
    def pop(self, key: str) -> str:
        """LPOP; raises KeyError when empty."""

    @abc.abstractmethod
    def queue_length(self, key: str) -> int: ...

    @abc.abstractmethod
    def blocking_pop_copy(self, key: str, dest: str, timeout: timedelta) -> str:
        """BRPOPLPUSH; raises TimeoutError on timeout."""

    @abc.abstractmethod
    def list_remove(self, key: str, value: str) -> None: ...

    @abc.abstractmethod
    def try_set(self, key: str, value: str, life: timedelta) -> str:
        """SETNX+GET: attempt to set; return the value now present
        (ours if we won, the incumbent's otherwise). Reference:
        storage/rediscache.go:171-178."""

    @abc.abstractmethod
    def put(self, key: str, value: str,
            life: Optional[timedelta] = None) -> None:
        """Unconditional SET, optionally with a TTL. The fleet
        coordinator's heartbeat/epoch primitives (ingest/fleet.py)
        need a last-writer-wins value slot — try_set (NX) can only
        publish a value once per key lifetime."""

    @abc.abstractmethod
    def get(self, key: str) -> Optional[str]:
        """Plain GET; None when absent or expired."""

    @abc.abstractmethod
    def keys_matching(self, pattern: str) -> Iterator[str]:
        """Stream keys matching a glob pattern (SCAN semantics)."""

    @abc.abstractmethod
    def store_log_state(self, log: CertificateLog) -> None: ...

    @abc.abstractmethod
    def load_log_state(self, short_url: str) -> Optional[CertificateLog]: ...


class StorageBackend(abc.ABC):
    """Durable storage. Reference: storage/types.go:46-68."""

    @abc.abstractmethod
    def mark_dirty(self, id_: str) -> None: ...

    @abc.abstractmethod
    def store_certificate_pem(
        self, serial: Serial, exp_date: ExpDate, issuer: Issuer, pem: bytes
    ) -> None: ...

    @abc.abstractmethod
    def store_log_state(self, log: CertificateLog) -> None: ...

    @abc.abstractmethod
    def store_known_certificate_list(
        self, issuer: Issuer, serials: list[Serial]
    ) -> None: ...

    @abc.abstractmethod
    def load_certificate_pem(
        self, serial: Serial, exp_date: ExpDate, issuer: Issuer
    ) -> bytes: ...

    @abc.abstractmethod
    def load_log_state(self, log_url: str) -> Optional[CertificateLog]: ...

    @abc.abstractmethod
    def allocate_exp_date_and_issuer(
        self, exp_date: ExpDate, issuer: Issuer
    ) -> None: ...

    @abc.abstractmethod
    def list_expiration_dates(self, not_before: datetime) -> list[ExpDate]: ...

    @abc.abstractmethod
    def list_issuers_for_expiration_date(self, exp_date: ExpDate) -> list[Issuer]: ...

    @abc.abstractmethod
    def list_serials_for_expiration_date_and_issuer(
        self, exp_date: ExpDate, issuer: Issuer
    ) -> list[Serial]: ...

    @abc.abstractmethod
    def stream_serials_for_expiration_date_and_issuer(
        self, exp_date: ExpDate, issuer: Issuer
    ) -> Iterator[UniqueCertIdentifier]: ...


class CertDatabase(abc.ABC):
    """The facade the sync engine stores through.

    Reference: storage/types.go:70-81.
    """

    @abc.abstractmethod
    def cleanup(self) -> None: ...

    @abc.abstractmethod
    def save_log_state(self, log: CertificateLog) -> None: ...

    @abc.abstractmethod
    def get_log_state(self, short_url: str) -> CertificateLog: ...

    @abc.abstractmethod
    def store(
        self,
        cert_der: bytes,
        issuer_der: bytes,
        log_url: str,
        entry_id: int,
    ) -> None:
        """Per-certificate map+reduce: dedup, metadata accumulation,
        allocation, PEM store, dirty-mark. Reference:
        storage/filesystemdatabase.go:158-211. Takes raw DER (the
        TPU-native framework's interchange format) rather than parsed
        objects."""

    @abc.abstractmethod
    def list_expiration_dates(self, not_before: datetime) -> list[ExpDate]: ...

    @abc.abstractmethod
    def list_issuers_for_expiration_date(self, exp_date: ExpDate) -> list[Issuer]: ...

    @abc.abstractmethod
    def get_known_certificates(
        self, exp_date: ExpDate, issuer: Issuer
    ) -> "KnownCertificates": ...

    @abc.abstractmethod
    def get_issuer_metadata(self, issuer: Issuer) -> "IssuerMetadata": ...

    @abc.abstractmethod
    def get_issuer_and_dates_from_cache(self) -> list[IssuerDate]: ...


def short_url_of(log_url: str) -> str:
    """Normalize a CT log URL to its short form (scheme stripped,
    trailing slash removed) — the reference keys log state by this
    (see cmd/ct-fetch/ct-fetch.go:253-257 usage of url.Host+url.Path)."""
    u = log_url.strip()
    for prefix in ("https://", "http://"):
        if u.startswith(prefix):
            u = u[len(prefix) :]
            break
    return u.rstrip("/")
