"""Redis-backed RemoteCache over a dependency-free RESP2 socket client.

Parity with the reference's RedisCache
(/root/reference/storage/rediscache.go): client-side retry (10
attempts, capped backoff, :22-28), an advisory check that
maxmemory_policy=noeviction (:44-55), hard failure on Redis OOM
(:57-65), set/TTL/queue/SETNX/scan operations, and log-state JSON KV
under `log::<shortURL>` (:180-204). Implemented directly on the RESP
protocol because no redis client library ships in this environment.
"""

from __future__ import annotations

import socket
import threading
import time
from datetime import datetime, timedelta, timezone
from typing import Iterator, Optional

from ct_mapreduce_tpu.core.types import CertificateLog
from ct_mapreduce_tpu.storage.interfaces import RemoteCache
from ct_mapreduce_tpu.telemetry import metrics


class RedisFatalError(RuntimeError):
    """Unrecoverable Redis condition (e.g. OOM with noeviction)."""


class RespClient:
    """Minimal RESP2 client: one socket, thread-safe command execution."""

    def __init__(self, host: str, port: int, timeout_s: float = 5.0):
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._buf = b""

    def _connect(self) -> None:
        self._sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout_s
        )
        self._buf = b""

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                finally:
                    self._sock = None

    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self._buf += chunk
        data, self._buf = self._buf[:n], self._buf[n:]
        return data

    def _read_reply(self):
        line = self._read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode("latin-1")
        if kind == b"-":
            msg = rest.decode("latin-1", "replace")
            if msg.startswith("OOM"):
                # Reference fatals the process on OOM (rediscache.go:57-65)
                raise RedisFatalError(msg)
            raise RuntimeError(f"redis error: {msg}")
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n == -1:
                return None
            data = self._read_exact(n)
            self._read_exact(2)  # trailing \r\n
            return data.decode("latin-1")
        if kind == b"*":
            n = int(rest)
            if n == -1:
                return None
            return [self._read_reply() for _ in range(n)]
        raise RuntimeError(f"unexpected RESP type {line!r}")

    def execute(self, *args: str | bytes | int, retries: int = 10):
        """Run one command with reconnect-and-retry (rediscache.go:22-28)."""
        payload_parts = [f"*{len(args)}\r\n".encode()]
        for a in args:
            if isinstance(a, int):
                a = str(a).encode()
            elif isinstance(a, str):
                a = a.encode("latin-1")
            payload_parts.append(b"$%d\r\n%s\r\n" % (len(a), a))
        payload = b"".join(payload_parts)

        backoff = 0.05
        last_exc: Exception = RuntimeError("unreachable")
        for _ in range(max(retries, 1)):
            try:
                with self._lock:
                    if self._sock is None:
                        self._connect()
                    self._sock.sendall(payload)
                    return self._read_reply()
            except RedisFatalError:
                raise
            except (OSError, ConnectionError) as exc:
                last_exc = exc
                self.close()
                time.sleep(backoff)
                backoff = min(backoff * 2, 5.0)  # 5s max (rediscache.go:24)
        raise last_exc


class RedisCache(RemoteCache):
    def __init__(self, host_port: str, timeout_s: float = 5.0):
        host, _, port = host_port.partition(":")
        self.client = RespClient(host, int(port or 6379), timeout_s)
        if self.client.execute("PING") != "PONG":
            raise ConnectionError(f"redis at {host_port} did not PONG")
        if not self.memory_policy_correct():
            import sys

            print(
                "WARNING: Redis maxmemory_policy should be noeviction "
                "(rediscache.go:44-55 parity warning)",
                file=sys.stderr,
            )

    def close(self) -> None:
        self.client.close()

    def memory_policy_correct(self) -> bool:
        info = self.client.execute("INFO", "memory") or ""
        for line in str(info).splitlines():
            if line.startswith("maxmemory_policy:"):
                return line.split(":", 1)[1].strip() == "noeviction"
        return True

    # -- sets ------------------------------------------------------------
    def exists(self, key: str) -> bool:
        return bool(self.client.execute("EXISTS", key))

    def set_insert(self, key: str, entry: str) -> bool:
        with metrics.measure("RedisCache", "SetInsert"):
            return self.client.execute("SADD", key, entry) == 1

    def set_remove(self, key: str, entry: str) -> bool:
        return self.client.execute("SREM", key, entry) == 1

    def set_contains(self, key: str, entry: str) -> bool:
        return self.client.execute("SISMEMBER", key, entry) == 1

    def set_list(self, key: str) -> list[str]:
        return list(self.client.execute("SMEMBERS", key) or [])

    def set_to_iter(self, key: str) -> Iterator[str]:
        cursor = "0"
        while True:
            cursor, members = self.client.execute("SSCAN", key, cursor, "COUNT", 512)
            yield from members
            if cursor == "0":
                break

    def set_cardinality(self, key: str) -> int:
        return int(self.client.execute("SCARD", key))

    # -- TTLs ------------------------------------------------------------
    def expire_at(self, key: str, exp_time: datetime) -> None:
        if exp_time.tzinfo is None:
            exp_time = exp_time.replace(tzinfo=timezone.utc)
        self.client.execute("EXPIREAT", key, int(exp_time.timestamp()))

    def expire_in(self, key: str, duration: timedelta) -> None:
        self.client.execute("EXPIRE", key, max(int(duration.total_seconds()), 1))

    # -- queues ----------------------------------------------------------
    def queue(self, key: str, identifier: str) -> int:
        return int(self.client.execute("RPUSH", key, identifier))

    def pop(self, key: str) -> str:
        result = self.client.execute("LPOP", key)
        if result is None:
            raise KeyError(key)
        return result

    def queue_length(self, key: str) -> int:
        return int(self.client.execute("LLEN", key))

    def blocking_pop_copy(self, key: str, dest: str, timeout: timedelta) -> str:
        result = self.client.execute(
            "BRPOPLPUSH", key, dest, max(int(timeout.total_seconds()), 1)
        )
        if result is None:
            raise TimeoutError(key)
        return result

    def list_remove(self, key: str, value: str) -> None:
        self.client.execute("LREM", key, 0, value)

    # -- SETNX / scan / log state ---------------------------------------
    def try_set(self, key: str, value: str, life: timedelta) -> str:
        # SET NX then GET (rediscache.go:171-178)
        self.client.execute(
            "SET", key, value, "NX", "PX", max(int(life.total_seconds() * 1000), 1)
        )
        current = self.client.execute("GET", key)
        return current if current is not None else value

    def put(self, key: str, value: str,
            life: Optional[timedelta] = None) -> None:
        if life is None:
            self.client.execute("SET", key, value)
        else:
            self.client.execute(
                "SET", key, value, "PX",
                max(int(life.total_seconds() * 1000), 1),
            )

    def get(self, key: str) -> Optional[str]:
        return self.client.execute("GET", key)

    def keys_matching(self, pattern: str) -> Iterator[str]:
        cursor = "0"
        while True:
            cursor, keys = self.client.execute(
                "SCAN", cursor, "MATCH", pattern, "COUNT", 512
            )
            yield from keys
            if cursor == "0":
                break

    def store_log_state(self, log: CertificateLog) -> None:
        self.client.execute("SET", f"log::{log.short_url}", log.to_json())

    def load_log_state(self, short_url: str) -> Optional[CertificateLog]:
        raw = self.client.execute("GET", f"log::{short_url}")
        return CertificateLog.from_json(raw) if raw else None
