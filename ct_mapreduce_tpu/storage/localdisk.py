"""Local-disk StorageBackend: one PEM file per certificate in a
date/issuer-sharded tree.

Reference: /root/reference/storage/localdiskbackend.go — layout
`<root>/<expDate>/<issuerID>/<serialID>` (:194-199), log state JSON at
`<root>/state/<base64url(shortURL)>` (:201-210), a dirty-marker file
per day directory (:89-91), listings by directory walk (:93-139).
Unlike the reference — whose serial streaming and PEM loading are
explicitly unimplemented (:172-182, :239-242) — this backend implements
both (the TPU drain path reads serials back for parity checks).
"""

from __future__ import annotations

import os
from datetime import datetime, timezone
from pathlib import Path
from typing import Iterator, Optional

from ct_mapreduce_tpu.core.types import (
    CertificateLog,
    ExpDate,
    Issuer,
    Serial,
    UniqueCertIdentifier,
    certificate_log_id_from_short_url,
)
from ct_mapreduce_tpu.storage.interfaces import StorageBackend

DIRTY_MARKER = ".dirty"
STATE_DIR = "state"


class LocalDiskBackend(StorageBackend):
    def __init__(self, root_path: str | os.PathLike, file_mode: int = 0o644):
        self.root = Path(root_path)
        self.file_mode = file_mode
        self.root.mkdir(parents=True, exist_ok=True)
        (self.root / STATE_DIR).mkdir(exist_ok=True)

    # -- paths ----------------------------------------------------------
    def _exp_dir(self, exp_date: ExpDate) -> Path:
        return self.root / exp_date.id()

    def _issuer_dir(self, exp_date: ExpDate, issuer: Issuer) -> Path:
        return self._exp_dir(exp_date) / issuer.id()

    def _cert_path(self, serial: Serial, exp_date: ExpDate, issuer: Issuer) -> Path:
        return self._issuer_dir(exp_date, issuer) / serial.id()

    # -- StorageBackend -------------------------------------------------
    def mark_dirty(self, id_: str) -> None:
        # id_ is a day-directory name (filesystemdatabase.go:141-144)
        target = self.root / id_
        target.mkdir(parents=True, exist_ok=True)
        (target / DIRTY_MARKER).touch()

    def store_certificate_pem(
        self, serial: Serial, exp_date: ExpDate, issuer: Issuer, pem: bytes
    ) -> None:
        path = self._cert_path(serial, exp_date, issuer)
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_bytes(pem)
        path.chmod(self.file_mode)

    def store_log_state(self, log: CertificateLog) -> None:
        path = self.root / STATE_DIR / certificate_log_id_from_short_url(log.short_url)
        path.write_text(log.to_json())

    def store_known_certificate_list(
        self, issuer: Issuer, serials: list[Serial]
    ) -> None:
        path = self.root / f"known-{issuer.id()}.json"
        path.write_text("[" + ",".join(s.to_json() for s in serials) + "]")

    def load_certificate_pem(
        self, serial: Serial, exp_date: ExpDate, issuer: Issuer
    ) -> bytes:
        return self._cert_path(serial, exp_date, issuer).read_bytes()

    def load_log_state(self, short_url: str) -> Optional[CertificateLog]:
        path = self.root / STATE_DIR / certificate_log_id_from_short_url(short_url)
        if not path.exists():
            return None
        return CertificateLog.from_json(path.read_text())

    def allocate_exp_date_and_issuer(self, exp_date: ExpDate, issuer: Issuer) -> None:
        self._issuer_dir(exp_date, issuer).mkdir(parents=True, exist_ok=True)

    def list_expiration_dates(self, not_before: datetime) -> list[ExpDate]:
        if not_before.tzinfo is None:
            not_before = not_before.replace(tzinfo=timezone.utc)
        # Truncate to midnight so same-day hour buckets are kept
        # (localdiskbackend.go:98)
        not_before = not_before.replace(hour=0, minute=0, second=0, microsecond=0)
        out = []
        for entry in sorted(self.root.iterdir()):
            if not entry.is_dir() or entry.name == STATE_DIR:
                continue
            try:
                exp = ExpDate.parse(entry.name)
            except ValueError:
                continue
            if not exp.is_expired_at(not_before):
                out.append(exp)
        return out

    def list_issuers_for_expiration_date(self, exp_date: ExpDate) -> list[Issuer]:
        exp_dir = self._exp_dir(exp_date)
        if not exp_dir.is_dir():
            return []
        return [
            Issuer.from_string(d.name)
            for d in sorted(exp_dir.iterdir())
            if d.is_dir()
        ]

    def list_serials_for_expiration_date_and_issuer(
        self, exp_date: ExpDate, issuer: Issuer
    ) -> list[Serial]:
        issuer_dir = self._issuer_dir(exp_date, issuer)
        if not issuer_dir.is_dir():
            return []
        out = []
        for f in sorted(issuer_dir.iterdir()):
            if f.name == DIRTY_MARKER or not f.is_file():
                continue
            out.append(Serial.from_id_string(f.name))
        return out

    def stream_serials_for_expiration_date_and_issuer(
        self, exp_date: ExpDate, issuer: Issuer
    ) -> Iterator[UniqueCertIdentifier]:
        for serial in self.list_serials_for_expiration_date_and_issuer(
            exp_date, issuer
        ):
            yield UniqueCertIdentifier(
                exp_date=exp_date, issuer=issuer, serial=serial
            )
