"""Per-(expDate, issuer) serial dedup set.

Reference: /root/reference/storage/knowncertificates.go. Key format
`serials::<expDate>::<issuerID>`; `was_unknown` is a set-insert whose
"newly added" result is the dedup bit; the key's TTL is set once to the
bucket's expiry time so Redis self-prunes expired buckets.

Serials are stored as raw bytes rendered latin-1 (Go stores the raw
byte string, knowncertificates.go:39).
"""

from __future__ import annotations

from ct_mapreduce_tpu.core.types import ExpDate, Issuer, Serial
from ct_mapreduce_tpu.storage.interfaces import RemoteCache

SERIALS_PREFIX = "serials"


def serials_key(exp_date: ExpDate, issuer: Issuer) -> str:
    return f"{SERIALS_PREFIX}::{exp_date.id()}::{issuer.id()}"


class KnownCertificates:
    def __init__(self, exp_date: ExpDate, issuer: Issuer, cache: RemoteCache):
        self.exp_date = exp_date
        self.issuer = issuer
        self.cache = cache
        self._expiry_set = False

    def id(self) -> str:
        return f"{self.exp_date.id()}::{self.issuer.id()}"

    def serial_id(self) -> str:
        return serials_key(self.exp_date, self.issuer)

    def was_unknown(self, serial: Serial) -> bool:
        """True iff this serial had not been seen before; subsequent
        calls with the same serial return False
        (knowncertificates.go:38-55)."""
        result = self.cache.set_insert(
            self.serial_id(), serial.binary_string().decode("latin-1")
        )
        if not self._expiry_set:
            self.cache.expire_at(self.serial_id(), self.exp_date.expire_time())
            self._expiry_set = True
        return result

    def count(self) -> int:
        return self.cache.set_cardinality(self.serial_id())

    def known(self) -> list[Serial]:
        """Drain the full serial set, re-deduplicating client-side
        because scans may replay members (knowncertificates.go:65-96)."""
        seen: set[str] = set()
        for member in self.cache.set_to_iter(self.serial_id()):
            seen.add(member)
        return [Serial.from_bytes(m.encode("latin-1")) for m in seen]
