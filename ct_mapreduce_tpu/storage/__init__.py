"""Storage layer: the CertDatabase facade, domain aggregates, pluggable
durable backends, and the remote-cache fabric (reference parity with
/root/reference/storage/)."""

from ct_mapreduce_tpu.storage.interfaces import (  # noqa: F401
    CertDatabase,
    RemoteCache,
    StorageBackend,
)
from ct_mapreduce_tpu.storage.mockcache import MockRemoteCache  # noqa: F401
from ct_mapreduce_tpu.storage.knowncerts import KnownCertificates  # noqa: F401
from ct_mapreduce_tpu.storage.issuermetadata import IssuerMetadata  # noqa: F401
from ct_mapreduce_tpu.storage.noop import NoopBackend  # noqa: F401
from ct_mapreduce_tpu.storage.localdisk import LocalDiskBackend  # noqa: F401
from ct_mapreduce_tpu.storage.mockbackend import MockBackend  # noqa: F401
from ct_mapreduce_tpu.storage.certdb import FilesystemDatabase  # noqa: F401
