"""Host-side batch packing and the fingerprint/meta schemas.

The device pipeline consumes fixed-shape batches; this module is the
single source of truth for their layout, shared by the device ops, the
host reference lane, and the tests:

- **Entry batch**: zero-padded DER bytes ``uint8[B, L]`` + per-lane
  true length, issuer index, and validity mask. ``L`` is chosen from
  power-of-two-ish buckets so XLA compiles a handful of shapes total
  (the streaming analog of the reference's fixed 1000-entry download
  batches, /root/reference/cmd/ct-fetch/ct-fetch.go:417).
- **Fingerprint message** (dedup key): ``expHour(4B BE) ‖
  issuerIdx(4B BE) ‖ serialLen(1B) ‖ serial(≤46B)`` hashed with
  SHA-256, low 128 bits kept. Equality of this message ⇔ equality of
  the reference's Redis member ``(serials::<exp>::<issuer>, serial)``
  triple (/root/reference/storage/knowncertificates.go:28-55), given
  the run's issuer registry.
- **Meta word**: ``issuerIdx(14b) | expHourOffset(18b)`` stored next
  to each table key so drains can rebuild exact per-(issuer, expDate)
  serial counts.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

import numpy as np

MAX_SERIAL_BYTES = 46  # fits a single SHA-256 block with the prefix
FP_MSG_BYTES = 9 + MAX_SERIAL_BYTES  # ≤ 55 ⇒ single block after padding

META_ISSUER_BITS = 14
META_HOUR_BITS = 18
MAX_ISSUERS = 1 << META_ISSUER_BITS
META_HOUR_SPAN = 1 << META_HOUR_BITS  # ~29.9 years of hour buckets

# Default epoch-hour base for the meta word: 2015-08-02T16:00Z. Any cert
# expiring within ~30 years of that is representable; others take the
# host lane.
DEFAULT_BASE_HOUR = 400_000

LENGTH_BUCKETS = (512, 768, 1024, 1536, 2048, 3072, 4096, 6144, 8192)


def length_bucket(n: int) -> int:
    for b in LENGTH_BUCKETS:
        if n <= b:
            return b
    raise ValueError(f"certificate of {n} bytes exceeds the largest bucket")


@dataclass
class PackedBatch:
    """A fixed-shape device batch (all NumPy; device_put by the caller)."""

    data: np.ndarray  # uint8[B, L]
    length: np.ndarray  # int32[B]
    issuer_idx: np.ndarray  # int32[B]
    valid: np.ndarray  # bool[B]

    @property
    def batch_size(self) -> int:
        return self.data.shape[0]


def pack_entries(
    entries: list[tuple[bytes, int]],
    batch_size: int | None = None,
    pad_len: int | None = None,
) -> PackedBatch:
    """Pack (der, issuer_idx) pairs into a device batch.

    Lanes beyond ``len(entries)`` are padding (valid=False). Entries
    longer than ``pad_len`` (when forced) raise — callers should route
    such certs to the host lane before packing.
    """
    n = len(entries)
    b = batch_size or n
    if n > b:
        raise ValueError(f"{n} entries > batch size {b}")
    maxlen = max((len(d) for d, _ in entries), default=1)
    l = pad_len or length_bucket(maxlen)
    if maxlen > l:
        raise ValueError(f"entry of {maxlen} bytes > pad length {l}")
    data = np.zeros((b, l), dtype=np.uint8)
    length = np.zeros((b,), dtype=np.int32)
    issuer_idx = np.zeros((b,), dtype=np.int32)
    valid = np.zeros((b,), dtype=bool)
    for i, (der, idx) in enumerate(entries):
        data[i, : len(der)] = np.frombuffer(der, dtype=np.uint8)
        length[i] = len(der)
        issuer_idx[i] = idx
        valid[i] = True
    return PackedBatch(data, length, issuer_idx, valid)


def pack_meta(issuer_idx: int, exp_hour: int, base_hour: int = DEFAULT_BASE_HOUR) -> int:
    off = exp_hour - base_hour
    if not (0 <= off < META_HOUR_SPAN):
        raise ValueError(f"exp hour {exp_hour} outside meta span from {base_hour}")
    if not (0 <= issuer_idx < MAX_ISSUERS):
        raise ValueError(f"issuer index {issuer_idx} out of range")
    return (issuer_idx << META_HOUR_BITS) | off


def unpack_meta(meta: int, base_hour: int = DEFAULT_BASE_HOUR) -> tuple[int, int]:
    """meta word → (issuer_idx, exp_hour)."""
    return meta >> META_HOUR_BITS, (meta & (META_HOUR_SPAN - 1)) + base_hour


def fingerprint_message(issuer_idx: int, exp_hour: int, serial: bytes) -> bytes:
    if len(serial) > MAX_SERIAL_BYTES:
        raise ValueError(f"serial of {len(serial)} bytes needs the host lane")
    return (
        int(exp_hour).to_bytes(4, "big", signed=True)
        + int(issuer_idx).to_bytes(4, "big")
        + bytes([len(serial)])
        + serial
    )


def fingerprint_host(issuer_idx: int, exp_hour: int, serial: bytes) -> tuple[int, ...]:
    """Host reference of the device fingerprint: 4 uint32 words.

    Must match :func:`ct_mapreduce_tpu.ops.pipeline.fingerprints`
    exactly — the kernel-parity tests enforce it.
    """
    digest = hashlib.sha256(fingerprint_message(issuer_idx, exp_hour, serial)).digest()
    return tuple(
        int.from_bytes(digest[16 + 4 * i : 20 + 4 * i], "big") for i in range(4)
    )


# FIPS 180-4 SHA-256 constants for the vectorized host fingerprint
# below (duplicated from ops/sha256.py rather than imported: core/
# stays jax-free, and the constants are spec values, not code).
_SHA_K = np.array(
    [
        0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5,
        0x3956C25B, 0x59F111F1, 0x923F82A4, 0xAB1C5ED5,
        0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
        0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174,
        0xE49B69C1, 0xEFBE4786, 0x0FC19DC6, 0x240CA1CC,
        0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
        0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7,
        0xC6E00BF3, 0xD5A79147, 0x06CA6351, 0x14292967,
        0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
        0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85,
        0xA2BFE8A1, 0xA81A664B, 0xC24B8B70, 0xC76C51A3,
        0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
        0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5,
        0x391C0CB3, 0x4ED8AA4A, 0x5B9CCA4F, 0x682E6FF3,
        0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
        0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
    ],
    dtype=np.uint32,
)

_SHA_H0 = np.array(
    [
        0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
        0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
    ],
    dtype=np.uint32,
)


def fingerprints_np(
    issuer_idx: np.ndarray,
    exp_hour: np.ndarray,
    serials: np.ndarray,
    serial_len: np.ndarray,
) -> np.ndarray:
    """Vectorized host mirror of the device fingerprint pipeline
    (:func:`ct_mapreduce_tpu.ops.pipeline.fingerprints` →
    ``sha256_fingerprint64``): ``uint32[n, 4]`` dedup-key words from
    the sidecar's compact per-lane fields, no device round trip.

    The sharded pre-parsed lane uses this to compute every lane's home
    shard ON THE HOST (routing is a pure function of the fingerprint),
    so sidecars partition per shard before H2D and no ``all_to_all``
    runs on device. Bytes of ``serials`` past ``serial_len`` must
    already be zero (the sidecar serial window guarantees it), exactly
    as the device path assumes.
    """
    n = int(len(issuer_idx))
    if n == 0:
        return np.zeros((0, 4), np.uint32)
    eh = np.asarray(exp_hour).astype(np.uint32)
    ii = np.asarray(issuer_idx).astype(np.uint32)
    slen = np.asarray(serial_len).astype(np.int64)
    msg = np.zeros((n, 64), np.uint8)
    for j, v in enumerate((eh >> 24, eh >> 16, eh >> 8, eh,
                           ii >> 24, ii >> 16, ii >> 8, ii)):
        msg[:, j] = (v & 0xFF).astype(np.uint8)
    msg[:, 8] = (slen & 0xFF).astype(np.uint8)
    msg[:, 9:9 + MAX_SERIAL_BYTES] = np.asarray(serials, np.uint8)
    msg_len = 9 + slen  # ≤ 55: single block after FIPS padding
    msg = np.where(np.arange(64)[None, :] == msg_len[:, None],
                   np.uint8(0x80), msg)
    bits = (msg_len * 8).astype(np.uint32)
    msg[:, 62] = ((bits >> 8) & 0xFF).astype(np.uint8)
    msg[:, 63] = (bits & 0xFF).astype(np.uint8)
    w4 = msg.reshape(n, 16, 4).astype(np.uint32)
    block = ((w4[:, :, 0] << 24) | (w4[:, :, 1] << 16)
             | (w4[:, :, 2] << 8) | w4[:, :, 3])

    def rotr(x: np.ndarray, r: int) -> np.ndarray:
        return ((x >> np.uint32(r)) | (x << np.uint32(32 - r))).astype(
            np.uint32)

    # Message schedule + 64 compression rounds, all wrapping uint32.
    w = np.zeros((64, n), np.uint32)
    w[:16] = block.T
    for t in range(16, 64):
        s0 = rotr(w[t - 15], 7) ^ rotr(w[t - 15], 18) ^ (
            w[t - 15] >> np.uint32(3))
        s1 = rotr(w[t - 2], 17) ^ rotr(w[t - 2], 19) ^ (
            w[t - 2] >> np.uint32(10))
        w[t] = w[t - 16] + s0 + w[t - 7] + s1
    a, b, c, d, e, f, g, h = (
        np.full((n,), _SHA_H0[i], np.uint32) for i in range(8))
    for t in range(64):
        s1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + _SHA_K[t] + w[t]
        s0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        h, g, f, e, d, c, b, a = g, f, e, d + t1, c, b, a, t1 + t2
    digest = np.stack([a, b, c, d, e, f, g, h], axis=1) + _SHA_H0[None, :]
    return digest[:, 4:]  # low 128 bits, like sha256_fingerprint64
