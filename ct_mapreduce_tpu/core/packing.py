"""Host-side batch packing and the fingerprint/meta schemas.

The device pipeline consumes fixed-shape batches; this module is the
single source of truth for their layout, shared by the device ops, the
host reference lane, and the tests:

- **Entry batch**: zero-padded DER bytes ``uint8[B, L]`` + per-lane
  true length, issuer index, and validity mask. ``L`` is chosen from
  power-of-two-ish buckets so XLA compiles a handful of shapes total
  (the streaming analog of the reference's fixed 1000-entry download
  batches, /root/reference/cmd/ct-fetch/ct-fetch.go:417).
- **Fingerprint message** (dedup key): ``expHour(4B BE) ‖
  issuerIdx(4B BE) ‖ serialLen(1B) ‖ serial(≤46B)`` hashed with
  SHA-256, low 128 bits kept. Equality of this message ⇔ equality of
  the reference's Redis member ``(serials::<exp>::<issuer>, serial)``
  triple (/root/reference/storage/knowncertificates.go:28-55), given
  the run's issuer registry.
- **Meta word**: ``issuerIdx(14b) | expHourOffset(18b)`` stored next
  to each table key so drains can rebuild exact per-(issuer, expDate)
  serial counts.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

import numpy as np

MAX_SERIAL_BYTES = 46  # fits a single SHA-256 block with the prefix
FP_MSG_BYTES = 9 + MAX_SERIAL_BYTES  # ≤ 55 ⇒ single block after padding

META_ISSUER_BITS = 14
META_HOUR_BITS = 18
MAX_ISSUERS = 1 << META_ISSUER_BITS
META_HOUR_SPAN = 1 << META_HOUR_BITS  # ~29.9 years of hour buckets

# Default epoch-hour base for the meta word: 2015-08-02T16:00Z. Any cert
# expiring within ~30 years of that is representable; others take the
# host lane.
DEFAULT_BASE_HOUR = 400_000

LENGTH_BUCKETS = (512, 768, 1024, 1536, 2048, 3072, 4096, 6144, 8192)


def length_bucket(n: int) -> int:
    for b in LENGTH_BUCKETS:
        if n <= b:
            return b
    raise ValueError(f"certificate of {n} bytes exceeds the largest bucket")


@dataclass
class PackedBatch:
    """A fixed-shape device batch (all NumPy; device_put by the caller)."""

    data: np.ndarray  # uint8[B, L]
    length: np.ndarray  # int32[B]
    issuer_idx: np.ndarray  # int32[B]
    valid: np.ndarray  # bool[B]

    @property
    def batch_size(self) -> int:
        return self.data.shape[0]


def pack_entries(
    entries: list[tuple[bytes, int]],
    batch_size: int | None = None,
    pad_len: int | None = None,
) -> PackedBatch:
    """Pack (der, issuer_idx) pairs into a device batch.

    Lanes beyond ``len(entries)`` are padding (valid=False). Entries
    longer than ``pad_len`` (when forced) raise — callers should route
    such certs to the host lane before packing.
    """
    n = len(entries)
    b = batch_size or n
    if n > b:
        raise ValueError(f"{n} entries > batch size {b}")
    maxlen = max((len(d) for d, _ in entries), default=1)
    l = pad_len or length_bucket(maxlen)
    if maxlen > l:
        raise ValueError(f"entry of {maxlen} bytes > pad length {l}")
    data = np.zeros((b, l), dtype=np.uint8)
    length = np.zeros((b,), dtype=np.int32)
    issuer_idx = np.zeros((b,), dtype=np.int32)
    valid = np.zeros((b,), dtype=bool)
    for i, (der, idx) in enumerate(entries):
        data[i, : len(der)] = np.frombuffer(der, dtype=np.uint8)
        length[i] = len(der)
        issuer_idx[i] = idx
        valid[i] = True
    return PackedBatch(data, length, issuer_idx, valid)


def pack_meta(issuer_idx: int, exp_hour: int, base_hour: int = DEFAULT_BASE_HOUR) -> int:
    off = exp_hour - base_hour
    if not (0 <= off < META_HOUR_SPAN):
        raise ValueError(f"exp hour {exp_hour} outside meta span from {base_hour}")
    if not (0 <= issuer_idx < MAX_ISSUERS):
        raise ValueError(f"issuer index {issuer_idx} out of range")
    return (issuer_idx << META_HOUR_BITS) | off


def unpack_meta(meta: int, base_hour: int = DEFAULT_BASE_HOUR) -> tuple[int, int]:
    """meta word → (issuer_idx, exp_hour)."""
    return meta >> META_HOUR_BITS, (meta & (META_HOUR_SPAN - 1)) + base_hour


def fingerprint_message(issuer_idx: int, exp_hour: int, serial: bytes) -> bytes:
    if len(serial) > MAX_SERIAL_BYTES:
        raise ValueError(f"serial of {len(serial)} bytes needs the host lane")
    return (
        int(exp_hour).to_bytes(4, "big", signed=True)
        + int(issuer_idx).to_bytes(4, "big")
        + bytes([len(serial)])
        + serial
    )


def fingerprint_host(issuer_idx: int, exp_hour: int, serial: bytes) -> tuple[int, ...]:
    """Host reference of the device fingerprint: 4 uint32 words.

    Must match :func:`ct_mapreduce_tpu.ops.pipeline.fingerprints`
    exactly — the kernel-parity tests enforce it.
    """
    digest = hashlib.sha256(fingerprint_message(issuer_idx, exp_hour, serial)).digest()
    return tuple(
        int.from_bytes(digest[16 + 4 * i : 20 + 4 * i], "big") for i in range(4)
    )
