"""Identity and value types for the CT map/reduce domain.

Behavioral contract mirrors the reference's value types
(/root/reference/storage/types.go:25-405): issuer identity is
base64url(SHA-256(SPKI)), serials preserve raw DER content bytes
(including leading zeros), expiration dates bucket to the hour (when
constructed from a time) or to day/day+hour (when parsed from strings),
and the composite string IDs are reproduced byte-for-byte so reports and
cache keys are interchangeable with the reference's.
"""

from __future__ import annotations

import base64
import binascii
import hashlib
import json
from dataclasses import dataclass, field
from datetime import datetime, timedelta, timezone
from typing import Optional

EXPIRATION_FORMAT = "%Y-%m-%d"
EXPIRATION_FORMAT_WITH_HOUR = "%Y-%m-%d-%H"

_MS = timedelta(milliseconds=1)


def _b64url(data: bytes) -> str:
    """URL-safe base64 *with* padding (Go base64.URLEncoding parity)."""
    return base64.urlsafe_b64encode(data).decode("ascii")


def _b64url_decode(s: str) -> bytes:
    return base64.urlsafe_b64decode(s)


def certificate_log_id_from_short_url(short_url: str) -> str:
    """Reference: storage/types.go:36-38 (CertificateLogIDFromShortURL)."""
    return _b64url(short_url.encode("utf-8"))


@dataclass
class CertificateLog:
    """Per-log ingestion checkpoint record.

    Reference: storage/types.go:25-42. Serialized as JSON with the same
    field names the Go struct produces, so checkpoints interoperate.
    """

    short_url: str
    max_entry: int = 0
    last_entry_time: Optional[datetime] = None
    last_update_time: Optional[datetime] = None

    def id(self) -> str:
        return certificate_log_id_from_short_url(self.short_url)

    def __str__(self) -> str:
        return (
            f"[{self.short_url}] MaxEntry={self.max_entry}, "
            f"LastEntryTime={self.last_entry_time} "
            f"LastUpdateTime={self.last_update_time}"
        )

    def to_json(self) -> str:
        def enc_time(t: Optional[datetime]) -> str:
            if t is None:
                return "0001-01-01T00:00:00Z"
            if t.tzinfo is None:
                t = t.replace(tzinfo=timezone.utc)  # naive means UTC everywhere here
            return t.astimezone(timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.%f").rstrip(
                "0"
            ).rstrip(".") + "Z"

        return json.dumps(
            {
                "ShortURL": self.short_url,
                "MaxEntry": self.max_entry,
                "LastEntryTime": enc_time(self.last_entry_time),
                "LastUpdateTime": enc_time(self.last_update_time),
            }
        )

    @classmethod
    def from_json(cls, raw: str) -> "CertificateLog":
        obj = json.loads(raw)

        def dec_time(s: Optional[str]) -> Optional[datetime]:
            if not s or s.startswith("0001-01-01"):
                return None
            s = s.rstrip("Z")
            # Go marshals time.Time as RFC3339Nano (up to 9 fractional
            # digits); strptime %f accepts at most 6 — truncate.
            if "." in s:
                head, frac = s.split(".", 1)
                s = f"{head}.{frac[:6]}" if frac else head
            for fmt in ("%Y-%m-%dT%H:%M:%S.%f", "%Y-%m-%dT%H:%M:%S"):
                try:
                    return datetime.strptime(s, fmt).replace(tzinfo=timezone.utc)
                except ValueError:
                    continue
            return None

        return cls(
            short_url=obj["ShortURL"],
            max_entry=int(obj.get("MaxEntry", 0)),
            last_entry_time=dec_time(obj.get("LastEntryTime")),
            last_update_time=dec_time(obj.get("LastUpdateTime")),
        )


@dataclass(frozen=True)
class SPKI:
    """Raw SubjectPublicKeyInfo bytes. Reference: storage/types.go:143-159."""

    spki: bytes

    def id(self) -> str:
        return _b64url(self.spki)

    def __str__(self) -> str:
        return binascii.hexlify(self.spki).decode("ascii")

    def sha256_digest_url_encoded_base64(self) -> str:
        return _b64url(hashlib.sha256(self.spki).digest())

    def sha256_digest(self) -> bytes:
        return hashlib.sha256(self.spki).digest()


@dataclass
class Issuer:
    """Issuer identity: lazy base64url(SHA-256(SPKI)).

    Reference: storage/types.go:104-141. Construct from an SPKI
    (`Issuer.from_spki`) or directly from an already-computed ID string
    (`Issuer.from_string`, the NewIssuerFromString analog).
    """

    _id: Optional[str] = None
    spki: Optional[SPKI] = None

    @classmethod
    def from_spki(cls, spki: bytes | SPKI) -> "Issuer":
        if isinstance(spki, bytes):
            spki = SPKI(spki)
        return cls(_id=None, spki=spki)

    @classmethod
    def from_string(cls, issuer_id: str) -> "Issuer":
        return cls(_id=issuer_id, spki=None)

    def id(self) -> str:
        if self._id is None:
            assert self.spki is not None, "Issuer has neither id nor SPKI"
            self._id = self.spki.sha256_digest_url_encoded_base64()
        return self._id

    def digest(self) -> bytes:
        """The raw 32-byte SHA-256(SPKI) — the device-side issuer key."""
        return _b64url_decode(self.id())

    def __hash__(self) -> int:
        return hash(self.id())

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Issuer) and self.id() == other.id()

    def __str__(self) -> str:
        return self.id()

    def to_json(self) -> str:
        return json.dumps(self.id())

    @classmethod
    def from_json(cls, raw: str) -> "Issuer":
        return cls.from_string(json.loads(raw))


@dataclass(frozen=True)
class Serial:
    """A certificate serial number as raw DER content bytes.

    Leading zeros are preserved (reference: storage/types.go:161-208 —
    NewSerial re-parses the TBSCertificate precisely so that serials
    like 00:AA:BB keep their leading 0x00 byte, which big-int based
    representations destroy; storage/types_test.go:81-101 is the spec).
    """

    serial: bytes

    @classmethod
    def from_bytes(cls, b: bytes) -> "Serial":
        return cls(bytes(b))

    @classmethod
    def from_hex(cls, s: str) -> "Serial":
        return cls(binascii.unhexlify(s))

    @classmethod
    def from_id_string(cls, s: str) -> "Serial":
        return cls(_b64url_decode(s))

    @classmethod
    def from_der_cert(cls, der: bytes) -> "Serial":
        from ct_mapreduce_tpu.core import der as derlib

        return cls(derlib.raw_serial_bytes(der))

    def id(self) -> str:
        return _b64url(self.serial)

    def hex_string(self) -> str:
        return binascii.hexlify(self.serial).decode("ascii")

    def binary_string(self) -> bytes:
        return self.serial

    def as_int(self) -> int:
        return int.from_bytes(self.serial, "big") if self.serial else 0

    def cmp(self, other: "Serial") -> int:
        return (self.serial > other.serial) - (self.serial < other.serial)

    def __lt__(self, other: "Serial") -> bool:
        return self.serial < other.serial

    def __str__(self) -> str:
        return self.hex_string()

    def to_json(self) -> str:
        return json.dumps(self.hex_string())

    @classmethod
    def from_json(cls, raw: str) -> "Serial":
        s = json.loads(raw)
        if not isinstance(s, str):
            raise ValueError("Expected surrounding quotes")
        return cls.from_hex(s)


@dataclass(frozen=True)
class ExpDate:
    """Expiration bucket: hour resolution when built from a time, hour or
    day resolution when parsed from a string.

    Reference: storage/types.go:333-384. `last_good` is the final instant
    still covered by the bucket (bucket end minus 1ms), used by
    IsExpiredAt.
    """

    date: datetime
    last_good: datetime = field(compare=False)
    hour_resolution: bool = True

    @classmethod
    def from_time(cls, t: datetime) -> "ExpDate":
        if t.tzinfo is None:
            t = t.replace(tzinfo=timezone.utc)
        t = t.astimezone(timezone.utc)
        trunc = t.replace(minute=0, second=0, microsecond=0)
        return cls(date=trunc, last_good=trunc - _MS, hour_resolution=True)

    @classmethod
    def parse(cls, s: str) -> "ExpDate":
        if len(s) > 10:
            try:
                t = datetime.strptime(s, EXPIRATION_FORMAT_WITH_HOUR).replace(
                    tzinfo=timezone.utc
                )
                return cls(
                    date=t, last_good=t + timedelta(hours=1) - _MS, hour_resolution=True
                )
            except ValueError:
                pass
        t = datetime.strptime(s, EXPIRATION_FORMAT).replace(tzinfo=timezone.utc)
        return cls(
            date=t, last_good=t + timedelta(hours=24) - _MS, hour_resolution=False
        )

    @classmethod
    def from_unix_hour(cls, hour: int) -> "ExpDate":
        """Build from the device-side int32 epoch-hour bucket."""
        t = datetime.fromtimestamp(hour * 3600, tz=timezone.utc)
        return cls(date=t, last_good=t - _MS, hour_resolution=True)

    def unix_hour(self) -> int:
        """The device-side int32 representation: hours since Unix epoch."""
        return int(self.date.timestamp()) // 3600

    def is_expired_at(self, t: datetime) -> bool:
        if t.tzinfo is None:
            t = t.replace(tzinfo=timezone.utc)
        return self.last_good < t

    def expire_time(self) -> datetime:
        return self.date

    def id(self) -> str:
        if self.hour_resolution:
            return self.date.strftime(EXPIRATION_FORMAT_WITH_HOUR)
        return self.date.strftime(EXPIRATION_FORMAT)

    def __str__(self) -> str:
        return self.id()

    def __hash__(self) -> int:
        return hash((self.date, self.hour_resolution))

    def __lt__(self, other: "ExpDate") -> bool:
        return self.date < other.date


@dataclass(frozen=True)
class UniqueCertIdentifier:
    """Composite `<expDate>::<issuerID>::<serialID>` identity.

    Reference: storage/types.go:273-306.
    """

    exp_date: ExpDate
    issuer: Issuer
    serial: Serial

    @classmethod
    def parse(cls, s: str) -> "UniqueCertIdentifier":
        parts = s.split("::")
        if len(parts) != 3:
            raise ValueError(f"Expected 3 parts, got {len(parts)}")
        return cls(
            exp_date=ExpDate.parse(parts[0]),
            issuer=Issuer.from_string(parts[1]),
            serial=Serial.from_id_string(parts[2]),
        )

    def __str__(self) -> str:
        return f"{self.exp_date.id()}::{self.issuer.id()}::{self.serial.id()}"

    def __hash__(self) -> int:
        return hash(str(self))


@dataclass(frozen=True)
class IssuerAndDate:
    """Composite `<expDate>/<issuerID>`. Reference: storage/types.go:308-331."""

    exp_date: ExpDate
    issuer: Issuer

    @classmethod
    def parse(cls, s: str) -> "IssuerAndDate":
        parts = s.split("/")
        if len(parts) != 2:
            raise ValueError(f"Unexpected number of parts: {len(parts)} from {s}")
        return cls(exp_date=ExpDate.parse(parts[0]), issuer=Issuer.from_string(parts[1]))

    def __str__(self) -> str:
        return f"{self.exp_date.id()}/{self.issuer.id()}"

    def __hash__(self) -> int:
        return hash(str(self))


@dataclass
class IssuerDate:
    """An issuer together with the expiration buckets it appears in.

    Reference: storage/types.go:402-405.
    """

    issuer: Issuer
    exp_dates: list[ExpDate] = field(default_factory=list)
