"""Minimal pure-Python DER/X.509 field extraction.

This module is the *reference lane* of the framework: a dependency-free
TLV walker that extracts exactly the fields the device kernel
(ct_mapreduce_tpu.ops.der_extract) extracts, so kernel parity tests can
compare against it byte-for-byte. It is also used on the host for
pathological certificates the fixed-window device parser rejects (the
reference tolerates per-entry parse errors and skips bad entries:
/root/reference/cmd/ct-fetch/ct-fetch.go:206-225, so a reject-to-host
lane is contract-compatible).

Field semantics mirror the reference:
  - raw serial content bytes including leading zeros
    (/root/reference/storage/types.go:165-178)
  - expiry bucketed to epoch-hour (/root/reference/storage/types.go:339-346)
  - issuer CommonName for the CN-prefix filter
    (/root/reference/cmd/ct-fetch/ct-fetch.go:56-62)
  - BasicConstraints CA flag (/root/reference/cmd/ct-fetch/ct-fetch.go:47-50)
  - CRL distribution point URIs
    (/root/reference/storage/issuermetadata.go:48-73)
"""

from __future__ import annotations

import base64
from dataclasses import dataclass, field
from datetime import datetime, timezone

# Universal tags
TAG_BOOLEAN = 0x01
TAG_INTEGER = 0x02
TAG_BIT_STRING = 0x03
TAG_OCTET_STRING = 0x04
TAG_OID = 0x06
TAG_UTF8_STRING = 0x0C
TAG_SEQUENCE = 0x30
TAG_SET = 0x31
TAG_PRINTABLE_STRING = 0x13
TAG_T61_STRING = 0x14
TAG_IA5_STRING = 0x16
TAG_UTC_TIME = 0x17
TAG_GENERALIZED_TIME = 0x18

OID_BASIC_CONSTRAINTS = bytes([0x55, 0x1D, 0x13])  # 2.5.29.19
OID_CRL_DISTRIBUTION_POINTS = bytes([0x55, 0x1D, 0x1F])  # 2.5.29.31
OID_COMMON_NAME = bytes([0x55, 0x04, 0x03])  # 2.5.4.3

# Attribute-type abbreviations used by Go's pkix.Name.String()
_DN_ABBREVIATIONS = {
    bytes([0x55, 0x04, 0x03]): "CN",
    bytes([0x55, 0x04, 0x05]): "SERIALNUMBER",
    bytes([0x55, 0x04, 0x06]): "C",
    bytes([0x55, 0x04, 0x07]): "L",
    bytes([0x55, 0x04, 0x08]): "ST",
    bytes([0x55, 0x04, 0x09]): "STREET",
    bytes([0x55, 0x04, 0x0A]): "O",
    bytes([0x55, 0x04, 0x0B]): "OU",
    bytes([0x55, 0x04, 0x11]): "POSTALCODE",
}


class DerError(ValueError):
    """Malformed DER structure."""


def read_tlv(buf: bytes, off: int) -> tuple[int, int, int]:
    """Read one TLV header at `off`.

    Returns (tag, content_length, content_offset). Only single-byte tags
    are supported (sufficient for X.509). Long-form lengths up to 4
    bytes are handled, matching the device kernel's window.
    """
    if off >= len(buf):
        raise DerError(f"TLV offset {off} beyond buffer of {len(buf)}")
    tag = buf[off]
    if tag & 0x1F == 0x1F:
        raise DerError(f"Multi-byte tag at {off} unsupported")
    if off + 1 >= len(buf):
        raise DerError("Truncated TLV length")
    first = buf[off + 1]
    if first < 0x80:
        length, content_off = first, off + 2
    else:
        n = first & 0x7F
        if n == 0 or n > 4:
            raise DerError(f"Unsupported length-of-length {n} at {off}")
        if off + 2 + n > len(buf):
            raise DerError("Truncated long-form length")
        length = int.from_bytes(buf[off + 2 : off + 2 + n], "big")
        content_off = off + 2 + n
    if content_off + length > len(buf):
        raise DerError(
            f"TLV at {off} (len {length}) overruns buffer of {len(buf)}"
        )
    return tag, length, content_off


def _skip(buf: bytes, off: int) -> int:
    """Offset just past the TLV starting at `off`."""
    _, length, content_off = read_tlv(buf, off)
    return content_off + length


def parse_time(tag: int, content: bytes) -> datetime:
    """Parse UTCTime / GeneralizedTime per RFC 5280."""
    s = content.decode("ascii")
    if tag == TAG_UTC_TIME:
        if not s.endswith("Z") or len(s) != 13:
            raise DerError(f"Bad UTCTime {s!r}")
        yy = int(s[0:2])
        year = 2000 + yy if yy < 50 else 1900 + yy
        rest = s[2:12]
    elif tag == TAG_GENERALIZED_TIME:
        if not s.endswith("Z") or len(s) != 15:
            raise DerError(f"Bad GeneralizedTime {s!r}")
        year = int(s[0:4])
        rest = s[4:14]
    else:
        raise DerError(f"Not a time tag: {tag:#x}")
    return datetime(
        year,
        int(rest[0:2]),
        int(rest[2:4]),
        int(rest[4:6]),
        int(rest[6:8]),
        int(rest[8:10]),
        tzinfo=timezone.utc,
    )


def _escape_dn_value(value: str) -> str:
    """RFC 2253-style escaping, matching Go pkix.Name.String()."""
    out = []
    for i, ch in enumerate(value):
        escape = ch in ",+\"\\<>;"
        if i == 0 and ch in " #":
            escape = True
        if i == len(value) - 1 and ch == " ":
            escape = True
        out.append("\\" + ch if escape else ch)
    return "".join(out)


def _decode_oid(content: bytes) -> str:
    """Dotted-decimal rendering of an OID's content bytes."""
    if not content:
        return ""
    subids = []
    val = 0
    for b in content:
        val = (val << 7) | (b & 0x7F)
        if not b & 0x80:
            subids.append(val)
            val = 0
    # First subidentifier encodes arc1*40+arc2 and may itself be
    # multi-byte (e.g. 2.999 → 1079 → 0x88 0x37).
    first = subids[0]
    if first < 80:
        parts = [first // 40, first % 40]
    else:
        parts = [2, first - 80]
    parts.extend(subids[1:])
    return ".".join(str(p) for p in parts)


@dataclass
class NameAttribute:
    oid: bytes
    value: str


def parse_name(buf: bytes, off: int) -> tuple[list[list[NameAttribute]], int]:
    """Parse an X.501 Name (SEQUENCE OF RDN) starting at `off`.

    Returns (RDNs in encoded order — each a list of attributes in
    encoded order, preserving multi-valued RDN structure — and the
    offset past the Name).
    """
    tag, length, content_off = read_tlv(buf, off)
    if tag != TAG_SEQUENCE:
        raise DerError(f"Name is not a SEQUENCE (tag {tag:#x})")
    end = content_off + length
    rdns: list[list[NameAttribute]] = []
    pos = content_off
    while pos < end:
        set_tag, set_len, set_off = read_tlv(buf, pos)
        if set_tag != TAG_SET:
            raise DerError(f"RDN is not a SET (tag {set_tag:#x})")
        set_end = set_off + set_len
        if set_end > end:
            raise DerError("RDN SET overruns its Name")
        apos = set_off
        rdn: list[NameAttribute] = []
        while apos < set_end:
            seq_tag, seq_len, seq_off = read_tlv(buf, apos)
            if seq_tag != TAG_SEQUENCE:
                raise DerError("AttributeTypeAndValue is not a SEQUENCE")
            seq_end = seq_off + seq_len
            if seq_end > set_end:
                raise DerError("AttributeTypeAndValue overruns its RDN")
            oid_tag, oid_len, oid_off = read_tlv(buf, seq_off)
            if oid_tag != TAG_OID:
                raise DerError("Attribute type is not an OID")
            oid = bytes(buf[oid_off : oid_off + oid_len])
            val_tag, val_len, val_off = read_tlv(buf, oid_off + oid_len)
            if val_off + val_len > seq_end:
                # A child escaping its parent TLV silently re-windows
                # identity bytes (the CN window would disagree with the
                # device walker's) — structurally invalid, reject.
                raise DerError("attribute value overruns its ATV frame")
            raw = bytes(buf[val_off : val_off + val_len])
            try:
                value = raw.decode("utf-8")
            except UnicodeDecodeError:
                value = raw.decode("latin-1")
            rdn.append(NameAttribute(oid=oid, value=value))
            apos = seq_off + seq_len
        rdns.append(rdn)
        pos = set_end
    return rdns, end


def render_dn_rfc4514(rdns: list[list[NameAttribute]]) -> str:
    """Structure-preserving RFC 4514 rendering: RDNs in reverse encoded
    order joined by ',', attributes within a multi-valued RDN joined by
    '+' (matches cryptography's rfc4514_string for known types)."""
    parts = []
    for rdn in reversed(rdns):
        parts.append(
            "+".join(
                f"{_DN_ABBREVIATIONS.get(a.oid, _decode_oid(a.oid))}"
                f"={_escape_dn_value(a.value)}"
                for a in rdn
            )
        )
    return ",".join(parts)


# pkix.Name.ToRDNSequence appends attribute groups in this fixed order
# (certificate-transparency-go x509/pkix, Go 1.13-era fork); String()
# then renders the sequence reversed.
_GO_CANONICAL_ORDER = [
    bytes([0x55, 0x04, 0x06]),  # C
    bytes([0x55, 0x04, 0x08]),  # ST
    bytes([0x55, 0x04, 0x07]),  # L
    bytes([0x55, 0x04, 0x09]),  # STREET
    bytes([0x55, 0x04, 0x11]),  # POSTALCODE
    bytes([0x55, 0x04, 0x0A]),  # O
    bytes([0x55, 0x04, 0x0B]),  # OU
    bytes([0x55, 0x04, 0x03]),  # CN (single-valued, last occurrence wins)
    bytes([0x55, 0x04, 0x05]),  # SERIALNUMBER (single-valued, last wins)
]
_GO_SINGLE_VALUED = {bytes([0x55, 0x04, 0x03]), bytes([0x55, 0x04, 0x05])}


def render_dn(rdns: list[list[NameAttribute]]) -> str:
    """Render a DN the way the reference observes it: Go
    pkix.Name.String() == FillFromRDNSequence → ToRDNSequence → String.

    Go *canonicalizes*: attributes are regrouped by type into the fixed
    order C, ST, L, STREET, POSTALCODE, O, OU, CN, SERIALNUMBER (one RDN
    per type, multi-valued types '+'-joined), the sequence is rendered
    reversed, CN/SERIALNUMBER keep only the last occurrence, and
    attribute types outside that set are dropped. The reference stores
    aCert.Issuer.String() into the issuer::<id> set
    (/root/reference/storage/issuermetadata.go:92-94), so cache parity
    requires reproducing this exactly rather than RFC 4514 structure
    preservation (see render_dn_rfc4514 for that)."""
    by_type: dict[bytes, list[str]] = {}
    for rdn in rdns:
        for attr in rdn:
            if attr.oid in _GO_SINGLE_VALUED:
                by_type[attr.oid] = [attr.value]  # last occurrence wins
            elif attr.oid in _DN_ABBREVIATIONS:
                by_type.setdefault(attr.oid, []).append(attr.value)
    parts = []
    for oid in reversed(_GO_CANONICAL_ORDER):
        values = by_type.get(oid)
        if not values:
            continue
        abbrev = _DN_ABBREVIATIONS[oid]
        parts.append(
            "+".join(f"{abbrev}={_escape_dn_value(v)}" for v in values)
        )
    return ",".join(parts)


def common_name(rdns: list[list[NameAttribute]]) -> str:
    """The CommonName, last occurrence winning — Go pkix
    FillFromRDNSequence overwrites CommonName per occurrence."""
    cn = ""
    for rdn in rdns:
        for attr in rdn:
            if attr.oid == OID_COMMON_NAME:
                cn = attr.value
    return cn


@dataclass
class CertFields:
    """Everything the pipeline needs from one certificate."""

    serial: bytes
    not_before: datetime
    not_after: datetime
    issuer_dn: str
    issuer_cn: str
    subject_dn: str
    spki: bytes
    is_ca: bool
    basic_constraints_valid: bool
    crl_distribution_points: list[str] = field(default_factory=list)
    # Structural offsets for device-kernel parity tests:
    serial_off: int = 0
    serial_len: int = 0
    spki_off: int = 0
    spki_len: int = 0
    not_after_tag_off: int = 0
    issuer_off: int = 0
    issuer_len: int = 0
    tbs_off: int = 0
    tbs_len: int = 0

    @property
    def not_after_unix_hour(self) -> int:
        return int(self.not_after.timestamp()) // 3600


def raw_serial_bytes(der: bytes) -> bytes:
    """Extract the raw serialNumber content bytes, preserving leading
    zeros (/root/reference/storage/types.go:165-178)."""
    _, _, cert_off = read_tlv(der, 0)
    _, _, tbs_off = read_tlv(der, cert_off)
    pos = tbs_off
    tag, _, _ = read_tlv(der, pos)
    if tag == 0xA0:  # [0] EXPLICIT version
        pos = _skip(der, pos)
    tag, length, content_off = read_tlv(der, pos)
    if tag != TAG_INTEGER:
        raise DerError(f"serialNumber is not an INTEGER (tag {tag:#x})")
    return bytes(der[content_off : content_off + length])


def _parse_general_names_uris(buf: bytes, off: int, end: int) -> list[str]:
    """Collect uniformResourceIdentifier ([6]) GeneralNames in [off, end)."""
    uris = []
    pos = off
    while pos < end:
        tag, length, content_off = read_tlv(buf, pos)
        if tag == 0x86:  # context [6] primitive: URI
            uris.append(bytes(buf[content_off : content_off + length]).decode("latin-1"))
        pos = content_off + length
    return uris


def _parse_crldp(buf: bytes, off: int) -> list[str]:
    """CRLDistributionPoints ::= SEQUENCE OF DistributionPoint."""
    uris: list[str] = []
    seq_tag, seq_len, seq_off = read_tlv(buf, off)
    if seq_tag != TAG_SEQUENCE:
        return uris
    end = seq_off + seq_len
    pos = seq_off
    while pos < end:
        dp_tag, dp_len, dp_off = read_tlv(buf, pos)
        if dp_tag == TAG_SEQUENCE:
            dp_end = dp_off + dp_len
            inner = dp_off
            while inner < dp_end:
                f_tag, f_len, f_off = read_tlv(buf, inner)
                if f_tag == 0xA0:  # [0] distributionPoint
                    g_tag, g_len, g_off = read_tlv(buf, f_off)
                    if g_tag == 0xA0:  # [0] fullName: GeneralNames
                        uris.extend(_parse_general_names_uris(buf, g_off, g_off + g_len))
                inner = f_off + f_len
        pos = dp_off + dp_len
    return uris


def _parse_basic_constraints(buf: bytes, off: int,
                             end: int | None = None) -> bool:
    """BasicConstraints ::= SEQUENCE { cA BOOLEAN DEFAULT FALSE, ... }"""
    tag, length, content_off = read_tlv(buf, off)
    if tag != TAG_SEQUENCE or length == 0:
        return False
    if end is not None and content_off + length > end:
        # The inner SEQUENCE escaping its extnValue window would read
        # the cA flag from bytes outside the extension (the device
        # walker's windowed read rejects this) — invalid, reject.
        raise DerError("BasicConstraints overruns its extnValue")
    b_tag, b_len, b_off = read_tlv(buf, content_off)
    return b_tag == TAG_BOOLEAN and b_len == 1 and buf[b_off] != 0x00


def parse_cert(der: bytes) -> CertFields:
    """Full field extraction from one DER certificate."""
    cert_tag, cert_len, cert_off = read_tlv(der, 0)
    if cert_tag != TAG_SEQUENCE:
        raise DerError("Certificate is not a SEQUENCE")
    tbs_tag, tbs_len, tbs_content = read_tlv(der, cert_off)
    if tbs_tag != TAG_SEQUENCE:
        raise DerError("TBSCertificate is not a SEQUENCE")

    pos = tbs_content
    tag, _, _ = read_tlv(der, pos)
    if tag == 0xA0:  # [0] EXPLICIT version
        pos = _skip(der, pos)

    # serialNumber
    tag, serial_len, serial_off = read_tlv(der, pos)
    if tag != TAG_INTEGER:
        raise DerError("serialNumber is not an INTEGER")
    serial = bytes(der[serial_off : serial_off + serial_len])
    pos = serial_off + serial_len

    # signature AlgorithmIdentifier
    pos = _skip(der, pos)

    # issuer Name
    issuer_start = pos
    issuer_rdns, pos = parse_name(der, pos)
    issuer_end = pos
    issuer_dn = render_dn(issuer_rdns)
    issuer_cn = common_name(issuer_rdns)

    # validity
    val_tag, val_len, val_off = read_tlv(der, pos)
    if val_tag != TAG_SEQUENCE:
        raise DerError("validity is not a SEQUENCE")
    nb_tag, nb_len, nb_off = read_tlv(der, val_off)
    not_before = parse_time(nb_tag, der[nb_off : nb_off + nb_len])
    na_tag_off = nb_off + nb_len
    na_tag, na_len, na_off = read_tlv(der, na_tag_off)
    not_after = parse_time(na_tag, der[na_off : na_off + na_len])
    pos = val_off + val_len

    # subject Name
    subject_rdns, pos = parse_name(der, pos)
    subject_dn = render_dn(subject_rdns)

    # subjectPublicKeyInfo — raw DER range (identity is SHA-256 of this:
    # /root/reference/storage/types.go:109-115,155-159)
    spki_start = pos
    spki_tag, spki_content_len, spki_content_off = read_tlv(der, pos)
    if spki_tag != TAG_SEQUENCE:
        raise DerError("subjectPublicKeyInfo is not a SEQUENCE")
    spki_end = spki_content_off + spki_content_len
    spki = bytes(der[spki_start:spki_end])
    pos = spki_end

    # optional issuerUniqueID [1], subjectUniqueID [2], extensions [3]
    is_ca = False
    bc_valid = False
    crldps: list[str] = []
    tbs_end = tbs_content + tbs_len
    while pos < tbs_end:
        tag, length, content_off = read_tlv(der, pos)
        if tag == 0xA3:  # [3] EXPLICIT extensions
            ext_seq_tag, ext_seq_len, ext_seq_off = read_tlv(der, content_off)
            if ext_seq_tag == TAG_SEQUENCE:
                epos = ext_seq_off
                eend = ext_seq_off + ext_seq_len
                while epos < eend:
                    e_tag, e_len, e_off = read_tlv(der, epos)
                    if e_tag == TAG_SEQUENCE:
                        o_tag, o_len, o_off = read_tlv(der, e_off)
                        if o_tag == TAG_OID:
                            oid = bytes(der[o_off : o_off + o_len])
                            vpos = o_off + o_len
                            v_tag, v_len, v_off = read_tlv(der, vpos)
                            if v_tag == TAG_BOOLEAN:  # critical flag
                                vpos = v_off + v_len
                                v_tag, v_len, v_off = read_tlv(der, vpos)
                            if v_off + v_len > e_off + e_len:
                                # extnValue overruns its Extension
                                # frame: structurally invalid (Go's
                                # asn1 errors on this; the device
                                # walker's windowed read rejects it
                                # too — caught by the mutation fuzz).
                                raise DerError(
                                    "extnValue overruns Extension frame"
                                )
                            if v_tag == TAG_OCTET_STRING:
                                if oid == OID_BASIC_CONSTRAINTS:
                                    bc_valid = True
                                    is_ca = _parse_basic_constraints(
                                        der, v_off, v_off + v_len)
                                elif oid == OID_CRL_DISTRIBUTION_POINTS:
                                    crldps = _parse_crldp(der, v_off)
                    epos = e_off + e_len
        pos = content_off + length

    return CertFields(
        serial=serial,
        not_before=not_before,
        not_after=not_after,
        issuer_dn=issuer_dn,
        issuer_cn=issuer_cn,
        subject_dn=subject_dn,
        spki=spki,
        is_ca=is_ca,
        basic_constraints_valid=bc_valid,
        crl_distribution_points=crldps,
        serial_off=serial_off,
        serial_len=serial_len,
        spki_off=spki_start,
        spki_len=spki_end - spki_start,
        not_after_tag_off=na_tag_off,
        issuer_off=issuer_start,
        issuer_len=issuer_end - issuer_start,
        tbs_off=cert_off,
        tbs_len=_skip(der, cert_off) - cert_off,
    )


def pem_to_der(pem: bytes | str) -> bytes:
    """Decode the first PEM CERTIFICATE block (or pass DER through)."""
    if isinstance(pem, str):
        pem = pem.encode("ascii")
    # Accept files with leading text (e.g. `openssl x509 -text` output)
    if b"-----BEGIN" not in pem:
        return bytes(pem)
    pem = pem[pem.index(b"-----BEGIN") :]
    lines = []
    inside = False
    for line in pem.splitlines():
        line = line.strip()
        if line.startswith(b"-----BEGIN"):
            inside = True
            continue
        if line.startswith(b"-----END"):
            break
        if inside:
            lines.append(line)
    return base64.b64decode(b"".join(lines))


def der_to_pem(der: bytes) -> bytes:
    b64 = base64.b64encode(der)
    body = b"\n".join(b64[i : i + 64] for i in range(0, len(b64), 64))
    return b"-----BEGIN CERTIFICATE-----\n" + body + b"\n-----END CERTIFICATE-----\n"
