"""Parser-divergence classification: the standing differential
harness seeded from the mutation fuzzers (ROADMAP item 5(a), after
ParsEval, arxiv 2405.18993).

Three parsers cover the same identity surface in this tree — the
device DER walker (:mod:`ct_mapreduce_tpu.ops.der_kernel`), the native
scalar sidecar extractor (:mod:`ct_mapreduce_tpu.native.leafpack`),
and the strict host parser (:mod:`ct_mapreduce_tpu.core.der`).
``classify_corpus`` runs a byte corpus through all of them and files
every certificate into the divergence buckets the fuzz suites (and a
future adversarial-corpus harness) report on:

- **device-accepts / host-rejects** — the walker's bounded leniency
  (it skips subtrees outside the identity surface, like Go x509's
  non-fatal tolerance). Bounded, never silently wrong: identity bytes
  are validated by the walker itself.
- **host-accepts / device-rejects** — walker strictness; these lanes
  take the exact host lane at ingest, so they cost throughput, not
  correctness.
- **verdict-mismatch** — both parsers accept but an identity-surface
  field differs (serial window, expiry hour, CA flag, SPKI window,
  issuer Name window, issuer-CN bytes, CRLDP presence/URLs). The
  HARD bucket: anything here silently corrupts identity keys and
  must stay at zero.
- **sidecar-undecidable** — the native extractor's ok bit disagrees
  with the walker's (either direction). The pre-parsed lane replays
  such lanes through the walker, so this bucket costs routing, not
  correctness — but drift here is the first sign the two ports have
  diverged.

``publish`` turns a report into the tracked metrics
(``parse.device_accept_rate`` and the ``parse.divergence_*`` counters,
docs/METRICS.md) so a long-running differential harness trends them.

The module imports lazily: ``core/`` stays jax-free until a corpus is
actually classified.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ct_mapreduce_tpu.telemetry.metrics import incr_counter, set_gauge


@dataclass
class DivergenceReport:
    total: int = 0
    device_accepts: int = 0
    host_accepts: int = 0
    both_accept: int = 0
    device_accept_host_reject: int = 0
    host_accept_device_reject: int = 0
    verdict_mismatch: int = 0
    # -1 = native extractor unavailable (bucket not measured).
    sidecar_undecidable: int = -1
    # Reproduction material for the non-empty hard buckets: one line
    # per offender, capped so a pathological corpus cannot flood.
    details: list[str] = field(default_factory=list)

    @property
    def device_accept_rate(self) -> float:
        return self.device_accepts / max(1, self.total)


def _walker_fields_mismatch(der: bytes, out, i: int, ref) -> str | None:
    """Identity-surface compare for one walker-accepted lane against
    the strict host parse; returns a repro string on mismatch."""
    from ct_mapreduce_tpu.core import der as hostder

    cn_bytes = der[int(out.issuer_cn_off[i]):
                   int(out.issuer_cn_off[i]) + int(out.issuer_cn_len[i])]
    try:  # mirror the host's utf-8-then-latin-1 decode (der.py)
        cn_str = cn_bytes.decode("utf-8")
    except UnicodeDecodeError:
        cn_str = cn_bytes.decode("latin-1")
    if bool(out.has_crldp[i]):
        try:
            dev_urls = hostder._parse_crldp(der, int(out.crldp_off[i]))
        except Exception:
            dev_urls = ["<unparseable>"]
    else:
        dev_urls = []
    if (int(out.serial_off[i]) != ref.serial_off
            or int(out.serial_len[i]) != ref.serial_len
            or int(out.not_after_hour[i]) != ref.not_after_unix_hour
            or bool(out.is_ca[i]) != ref.is_ca
            or int(out.spki_off[i]) != ref.spki_off
            or int(out.spki_len[i]) != ref.spki_len
            or int(out.issuer_off[i]) != ref.issuer_off
            or int(out.issuer_len[i]) != ref.issuer_len
            or cn_str != ref.issuer_cn
            or bool(out.has_crldp[i]) != bool(ref.crl_distribution_points)
            or sorted(dev_urls) != sorted(ref.crl_distribution_points)):
        return (
            f"lane {i} dev=(so={int(out.serial_off[i])} "
            f"sl={int(out.serial_len[i])} "
            f"nah={int(out.not_after_hour[i])} ca={bool(out.is_ca[i])} "
            f"po={int(out.spki_off[i])} pl={int(out.spki_len[i])}) "
            f"host=(so={ref.serial_off} sl={ref.serial_len} "
            f"nah={ref.not_after_unix_hour} ca={ref.is_ca} "
            f"po={ref.spki_off} pl={ref.spki_len}) der={der.hex()}"
        )
    return None


def classify_corpus(ders: list[bytes], pad_to: int = 1024,
                    max_details: int = 20) -> DivergenceReport:
    """Run every parser over the corpus and fill the buckets. Entries
    longer than ``pad_to`` are the caller's problem (route them to a
    wider bucket first, like the ingest path does)."""
    from ct_mapreduce_tpu.core import der as hostder
    from ct_mapreduce_tpu.ops import der_kernel

    n = len(ders)
    data = np.zeros((n, pad_to), np.uint8)
    length = np.zeros((n,), np.int32)
    for i, d in enumerate(ders):
        data[i, : len(d)] = np.frombuffer(d, np.uint8)
        length[i] = len(d)
    out = der_kernel.parse_certs(data, length)
    ok = np.asarray(out.ok)

    report = DivergenceReport(total=n)
    report.device_accepts = int(ok.sum())
    for i, der in enumerate(ders):
        try:
            ref = hostder.parse_cert(der)
        except Exception:
            ref = None
        if ref is not None:
            report.host_accepts += 1
        if ok[i] and ref is None:
            report.device_accept_host_reject += 1
        elif not ok[i] and ref is not None:
            report.host_accept_device_reject += 1
        elif ok[i] and ref is not None:
            report.both_accept += 1
            repro = _walker_fields_mismatch(der, out, i, ref)
            if repro is not None:
                report.verdict_mismatch += 1
                if len(report.details) < max_details:
                    report.details.append("MISMATCH " + repro)

    try:
        from ct_mapreduce_tpu.native import available, leafpack

        native_ok = available()
    except Exception:
        native_ok = False
    if native_ok:
        sc = leafpack.extract_sidecars(data, length)
        sc_ok = np.asarray(sc.ok).astype(bool)
        report.sidecar_undecidable = int((sc_ok ^ ok).sum())
    return report


# -- grammar-aware structured mutators (ParsEval methodology) ------------
#
# Single-byte XOR fuzz mostly produces garbage both parsers reject in
# the same place; the disagreement-inducing corpora of arxiv
# 2405.18993 are STRUCTURALLY plausible — valid TLV trees with one
# inconsistent length, or a nested element cut short while the outer
# frames still claim the old size. These mutators operate on the
# parsed TLV structure, not on random byte positions.


def iter_tlvs(der: bytes, max_depth: int = 6) -> list[tuple]:
    """Best-effort DER TLV walk: [(tag_off, len_off, header_len,
    content_len, depth)] for every element reachable under well-formed
    headers (single-byte tags; short / 0x81 / 0x82 length forms — the
    forms the identity surface uses). Stops quietly at malformed
    regions: mutants are produced FROM valid certs, so the walk sees
    the full tree there."""
    out: list[tuple] = []

    def walk(off: int, end: int, depth: int) -> None:
        while off + 2 <= end:
            tag = der[off]
            len_off = off + 1
            first = der[len_off]
            if first < 0x80:
                hdr, clen = 2, first
            elif first == 0x81 and len_off + 1 < end:
                hdr, clen = 3, der[len_off + 1]
            elif first == 0x82 and len_off + 2 < end:
                hdr = 4
                clen = (der[len_off + 1] << 8) | der[len_off + 2]
            else:
                return  # indefinite/absurd length form: stop here
            if off + hdr + clen > end:
                return
            out.append((off, len_off, hdr, clen, depth))
            constructed = bool(tag & 0x20)
            if constructed and depth < max_depth and clen:
                walk(off + hdr, off + hdr + clen, depth + 1)
            off += hdr + clen

    walk(0, len(der), 0)
    return out


def mutate_length_field(der: bytes, rng) -> bytes:
    """Length-field surgery: pick one TLV and rewrite its length
    encoding — off-by-one, a random value, or a long↔short form flip
    (which inserts/removes a header byte WITHOUT fixing any outer
    frame's length). The result is a tree whose frames disagree about
    where elements end — the classic parser-divergence shape."""
    tlvs = iter_tlvs(der)
    if not tlvs:
        return der
    b = bytearray(der)
    _, len_off, hdr, clen, _ = tlvs[int(rng.integers(len(tlvs)))]
    mode = int(rng.integers(4))
    if mode == 0:  # off-by-one (either direction)
        delta = 1 if rng.integers(2) else -1
        if hdr == 2:
            b[len_off] = (b[len_off] + delta) % 0x80
        elif hdr == 3:
            b[len_off + 1] = (b[len_off + 1] + delta) % 256
        else:
            b[len_off + 2] = (b[len_off + 2] + delta) % 256
    elif mode == 1:  # random length value, same form
        if hdr == 2:
            b[len_off] = int(rng.integers(0x80))
        else:
            b[len_off + hdr - 2] = int(rng.integers(256))
    elif mode == 2 and hdr == 2:  # short -> long form 0x81 (inserts
        # a byte; outer lengths now lie by one)
        b[len_off:len_off + 1] = bytes([0x81, clen])
    else:  # long -> shorter form (drops a byte), or minimal tweak
        if hdr == 4:
            b[len_off:len_off + 3] = bytes([0x81, min(clen, 255)])
        elif hdr == 3:
            b[len_off:len_off + 2] = bytes([clen & 0x7F])
        else:
            b[len_off] = (b[len_off] ^ 0x01) % 0x80
    return bytes(b)


def mutate_truncate_tlv(der: bytes, rng) -> bytes:
    """Nested-TLV truncation/extension: splice bytes out of (or junk
    into) one NESTED element's content while every enclosing frame
    keeps its original length claim — the inner element is now too
    short (or too long) for the tree around it."""
    tlvs = [t for t in iter_tlvs(der) if t[4] >= 1 and t[3] > 0]
    if not tlvs:
        return der
    off, _, hdr, clen, _ = tlvs[int(rng.integers(len(tlvs)))]
    content = off + hdr
    if rng.integers(2) or clen < 2:  # extend with junk bytes
        k = int(rng.integers(1, 9))
        junk = rng.integers(0, 256, k, dtype=np.uint8).tobytes()
        cut = content + int(rng.integers(clen + 1))
        return der[:cut] + junk + der[cut:]
    # truncate: drop a tail slice of the content
    k = int(rng.integers(1, max(2, clen // 2 + 1)))
    return der[:content + clen - k] + der[content + clen:]


def grammar_mutants(bases: list[bytes], rng, n: int) -> list[bytes]:
    """``n`` structured mutants over ``bases``, half per mutator —
    the corpus shape the standing ParsEval-style campaign feeds
    through :func:`classify_corpus` + :func:`publish`."""
    out = []
    for i in range(n):
        base = bases[int(rng.integers(len(bases)))]
        mut = (mutate_length_field if i % 2 == 0
               else mutate_truncate_tlv)
        out.append(mut(base, rng))
    return out


def publish(report: DivergenceReport) -> None:
    """Emit the tracked metrics for one classified corpus. Counters
    accumulate across corpora; the accept-rate gauge reflects the
    latest corpus (the number dashboards trend across fuzz rounds)."""
    set_gauge("parse", "device_accept_rate",
              value=report.device_accept_rate)
    incr_counter("parse", "divergence_device_accept_host_reject",
                 value=float(report.device_accept_host_reject))
    incr_counter("parse", "divergence_host_accept_device_reject",
                 value=float(report.host_accept_device_reject))
    incr_counter("parse", "divergence_verdict_mismatch",
                 value=float(report.verdict_mismatch))
    if report.sidecar_undecidable >= 0:
        incr_counter("parse", "divergence_sidecar_undecidable",
                     value=float(report.sidecar_undecidable))


# -- trend persistence (ROADMAP 5(a)) ------------------------------------

TREND_FORMAT = "CTMRDV01"


def record_trend(report: DivergenceReport, path: str,
                 corpus: str = "fuzz") -> dict:
    """Append one classified run's bucket counts to the JSON trend
    file at ``path`` (created if missing) and return the updated
    document. Runs are tagged with their ``corpus``: ``fuzz`` (the
    synthesized mutation corpora) pins ``floorDeviceAcceptRate`` on
    its first run, ``real`` (recorded-shard DER — round 24) pins
    ``floorRealAcceptRate`` separately, because a mutation corpus is
    built to be mostly rejected while a real shard should be almost
    entirely accepted — one floor cannot grade both. Later runs only
    append — each floor is a ratchet an operator (or a deliberate
    re-baseline) moves, never a harness run. Written tmp+replace like
    every durable artifact in the tree."""
    import json as _json
    import os as _os
    import tempfile as _tempfile

    doc: dict = {"format": TREND_FORMAT,
                 "floorDeviceAcceptRate": None, "runs": []}
    if _os.path.exists(path):
        with open(path, encoding="utf-8") as fh:
            doc = _json.load(fh)
        if doc.get("format") != TREND_FORMAT:
            raise ValueError(f"unknown trend format in {path}: "
                             f"{doc.get('format')!r}")
    entry = {
        "run": len(doc["runs"]) + 1,
        "corpus": corpus,
        "total": report.total,
        "deviceAccepts": report.device_accepts,
        "hostAccepts": report.host_accepts,
        "bothAccept": report.both_accept,
        "deviceAcceptHostReject": report.device_accept_host_reject,
        "hostAcceptDeviceReject": report.host_accept_device_reject,
        "verdictMismatch": report.verdict_mismatch,
        "sidecarUndecidable": report.sidecar_undecidable,
        "deviceAcceptRate": round(report.device_accept_rate, 6),
    }
    doc["runs"].append(entry)
    floor_key = ("floorRealAcceptRate" if corpus == "real"
                 else "floorDeviceAcceptRate")
    if doc.get(floor_key) is None:
        doc[floor_key] = entry["deviceAcceptRate"]
    fd, tmp = _tempfile.mkstemp(
        prefix=_os.path.basename(path) + ".tmp.",
        dir=_os.path.dirname(_os.path.abspath(path)))
    try:
        with _os.fdopen(fd, "w", encoding="utf-8") as fh:
            _json.dump(doc, fh, indent=1, sort_keys=True)
            fh.write("\n")
        _os.replace(tmp, path)
    except BaseException:
        import contextlib as _contextlib
        with _contextlib.suppress(OSError):
            _os.unlink(tmp)
        raise
    return doc


def trend_floor(path: str, corpus: str = "fuzz"):
    """The recorded accept-rate floor at ``path`` for the given
    corpus class (``fuzz`` → ``floorDeviceAcceptRate``, ``real`` →
    ``floorRealAcceptRate``), or None when none has been recorded
    yet. The tier-1 gates assert a fresh harness run never drops
    below its class's floor."""
    import json as _json
    import os as _os

    if not _os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as fh:
        doc = _json.load(fh)
    if doc.get("format") != TREND_FORMAT:
        raise ValueError(f"unknown trend format in {path}: "
                         f"{doc.get('format')!r}")
    return doc.get("floorRealAcceptRate" if corpus == "real"
                   else "floorDeviceAcceptRate")
