"""Parser-divergence classification: the standing differential
harness seeded from the mutation fuzzers (ROADMAP item 5(a), after
ParsEval, arxiv 2405.18993).

Three parsers cover the same identity surface in this tree — the
device DER walker (:mod:`ct_mapreduce_tpu.ops.der_kernel`), the native
scalar sidecar extractor (:mod:`ct_mapreduce_tpu.native.leafpack`),
and the strict host parser (:mod:`ct_mapreduce_tpu.core.der`).
``classify_corpus`` runs a byte corpus through all of them and files
every certificate into the divergence buckets the fuzz suites (and a
future adversarial-corpus harness) report on:

- **device-accepts / host-rejects** — the walker's bounded leniency
  (it skips subtrees outside the identity surface, like Go x509's
  non-fatal tolerance). Bounded, never silently wrong: identity bytes
  are validated by the walker itself.
- **host-accepts / device-rejects** — walker strictness; these lanes
  take the exact host lane at ingest, so they cost throughput, not
  correctness.
- **verdict-mismatch** — both parsers accept but an identity-surface
  field differs (serial window, expiry hour, CA flag, SPKI window,
  issuer Name window, issuer-CN bytes, CRLDP presence/URLs). The
  HARD bucket: anything here silently corrupts identity keys and
  must stay at zero.
- **sidecar-undecidable** — the native extractor's ok bit disagrees
  with the walker's (either direction). The pre-parsed lane replays
  such lanes through the walker, so this bucket costs routing, not
  correctness — but drift here is the first sign the two ports have
  diverged.

``publish`` turns a report into the tracked metrics
(``parse.device_accept_rate`` and the ``parse.divergence_*`` counters,
docs/METRICS.md) so a long-running differential harness trends them.

The module imports lazily: ``core/`` stays jax-free until a corpus is
actually classified.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ct_mapreduce_tpu.telemetry.metrics import incr_counter, set_gauge


@dataclass
class DivergenceReport:
    total: int = 0
    device_accepts: int = 0
    host_accepts: int = 0
    both_accept: int = 0
    device_accept_host_reject: int = 0
    host_accept_device_reject: int = 0
    verdict_mismatch: int = 0
    # -1 = native extractor unavailable (bucket not measured).
    sidecar_undecidable: int = -1
    # Reproduction material for the non-empty hard buckets: one line
    # per offender, capped so a pathological corpus cannot flood.
    details: list[str] = field(default_factory=list)

    @property
    def device_accept_rate(self) -> float:
        return self.device_accepts / max(1, self.total)


def _walker_fields_mismatch(der: bytes, out, i: int, ref) -> str | None:
    """Identity-surface compare for one walker-accepted lane against
    the strict host parse; returns a repro string on mismatch."""
    from ct_mapreduce_tpu.core import der as hostder

    cn_bytes = der[int(out.issuer_cn_off[i]):
                   int(out.issuer_cn_off[i]) + int(out.issuer_cn_len[i])]
    try:  # mirror the host's utf-8-then-latin-1 decode (der.py)
        cn_str = cn_bytes.decode("utf-8")
    except UnicodeDecodeError:
        cn_str = cn_bytes.decode("latin-1")
    if bool(out.has_crldp[i]):
        try:
            dev_urls = hostder._parse_crldp(der, int(out.crldp_off[i]))
        except Exception:
            dev_urls = ["<unparseable>"]
    else:
        dev_urls = []
    if (int(out.serial_off[i]) != ref.serial_off
            or int(out.serial_len[i]) != ref.serial_len
            or int(out.not_after_hour[i]) != ref.not_after_unix_hour
            or bool(out.is_ca[i]) != ref.is_ca
            or int(out.spki_off[i]) != ref.spki_off
            or int(out.spki_len[i]) != ref.spki_len
            or int(out.issuer_off[i]) != ref.issuer_off
            or int(out.issuer_len[i]) != ref.issuer_len
            or cn_str != ref.issuer_cn
            or bool(out.has_crldp[i]) != bool(ref.crl_distribution_points)
            or sorted(dev_urls) != sorted(ref.crl_distribution_points)):
        return (
            f"lane {i} dev=(so={int(out.serial_off[i])} "
            f"sl={int(out.serial_len[i])} "
            f"nah={int(out.not_after_hour[i])} ca={bool(out.is_ca[i])} "
            f"po={int(out.spki_off[i])} pl={int(out.spki_len[i])}) "
            f"host=(so={ref.serial_off} sl={ref.serial_len} "
            f"nah={ref.not_after_unix_hour} ca={ref.is_ca} "
            f"po={ref.spki_off} pl={ref.spki_len}) der={der.hex()}"
        )
    return None


def classify_corpus(ders: list[bytes], pad_to: int = 1024,
                    max_details: int = 20) -> DivergenceReport:
    """Run every parser over the corpus and fill the buckets. Entries
    longer than ``pad_to`` are the caller's problem (route them to a
    wider bucket first, like the ingest path does)."""
    from ct_mapreduce_tpu.core import der as hostder
    from ct_mapreduce_tpu.ops import der_kernel

    n = len(ders)
    data = np.zeros((n, pad_to), np.uint8)
    length = np.zeros((n,), np.int32)
    for i, d in enumerate(ders):
        data[i, : len(d)] = np.frombuffer(d, np.uint8)
        length[i] = len(d)
    out = der_kernel.parse_certs(data, length)
    ok = np.asarray(out.ok)

    report = DivergenceReport(total=n)
    report.device_accepts = int(ok.sum())
    for i, der in enumerate(ders):
        try:
            ref = hostder.parse_cert(der)
        except Exception:
            ref = None
        if ref is not None:
            report.host_accepts += 1
        if ok[i] and ref is None:
            report.device_accept_host_reject += 1
        elif not ok[i] and ref is not None:
            report.host_accept_device_reject += 1
        elif ok[i] and ref is not None:
            report.both_accept += 1
            repro = _walker_fields_mismatch(der, out, i, ref)
            if repro is not None:
                report.verdict_mismatch += 1
                if len(report.details) < max_details:
                    report.details.append("MISMATCH " + repro)

    try:
        from ct_mapreduce_tpu.native import available, leafpack

        native_ok = available()
    except Exception:
        native_ok = False
    if native_ok:
        sc = leafpack.extract_sidecars(data, length)
        sc_ok = np.asarray(sc.ok).astype(bool)
        report.sidecar_undecidable = int((sc_ok ^ ok).sum())
    return report


def publish(report: DivergenceReport) -> None:
    """Emit the tracked metrics for one classified corpus. Counters
    accumulate across corpora; the accept-rate gauge reflects the
    latest corpus (the number dashboards trend across fuzz rounds)."""
    set_gauge("parse", "device_accept_rate",
              value=report.device_accept_rate)
    incr_counter("parse", "divergence_device_accept_host_reject",
                 value=float(report.device_accept_host_reject))
    incr_counter("parse", "divergence_host_accept_device_reject",
                 value=float(report.host_accept_device_reject))
    incr_counter("parse", "divergence_verdict_mismatch",
                 value=float(report.verdict_mismatch))
    if report.sidecar_undecidable >= 0:
        incr_counter("parse", "divergence_sidecar_undecidable",
                     value=float(report.sidecar_undecidable))
