"""Identity and value types, DER parsing, and the packed batch schema."""

from ct_mapreduce_tpu.core.types import (  # noqa: F401
    CertificateLog,
    ExpDate,
    Issuer,
    IssuerAndDate,
    IssuerDate,
    Serial,
    SPKI,
    UniqueCertIdentifier,
    certificate_log_id_from_short_url,
)
