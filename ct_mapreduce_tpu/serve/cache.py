"""Hot-serial result cache: the layer in FRONT of the micro-batcher.

Real membership traffic is zipf-shaped — a small set of serials (the
big CDNs' current certificates, a crawler's working set) accounts for
most probes — so the cheapest batch is the one never formed. The cache
memoizes whole answers ``(known, epoch, capture_wall)`` keyed on the
query identity tuple ``(issuer_idx, exp_hour, serial_bytes)``. That
tuple is the exact preimage of the 128-bit table fingerprint (one
identity ⇒ one fingerprint, modulo the collision odds the dedup table
itself already accepts), so caching on it is equivalent to caching on
``(epoch, fingerprint)`` while skipping the SHA-256 pass entirely on a
hit — the point of the cache is to do no per-lane work at all.

Validity is epoch-floored, not TTL'd: an entry computed at epoch ``e``
may be served only while ``e >= floor_epoch`` — the minimum epoch
across the replica pool's live views. Serving such an entry is
indistinguishable from the round-robin dispatch having picked the
pool's stalest replica, which is always legal; once every replica has
refreshed past ``e`` the entry can never be served again (ghost
answers across epochs are impossible BY KEY, not by timer). A bump of
the pool's floor therefore invalidates by construction — there is no
explicit flush path to forget.

Membership is monotone (serials are never deleted), so a cached
``known=True`` can never flip; a cached ``known=False`` can become
stale-true, which is exactly the staleness the pool already exposes —
the hit carries its view's epoch and capture wall so the response's
``staleness_s`` stays honest.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional

from ct_mapreduce_tpu.telemetry.metrics import set_gauge


class CacheEntry:
    __slots__ = ("known", "epoch", "created_wall")

    def __init__(self, known: bool, epoch: int, created_wall: float) -> None:
        self.known = known
        self.epoch = epoch
        self.created_wall = created_wall


class HotSerialCache:
    """Bounded LRU of membership answers, epoch-floor validated.

    Thread-safe (query_raw runs on every HTTP handler thread); all
    operations are O(1) dict moves. ``capacity <= 0`` disables —
    every ``get`` misses and ``put`` is a no-op — so callers need no
    branching."""

    def __init__(self, capacity: int = 4096) -> None:
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._entries: OrderedDict[tuple, CacheEntry] = OrderedDict()
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def get(self, key: tuple, floor_epoch: int) -> Optional[CacheEntry]:
        """The entry for ``key`` if one exists at epoch >= the pool's
        floor; an entry every replica has refreshed past is evicted on
        probe (it could answer staler than anything the pool would)."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.misses += 1
                return None
            if e.epoch < floor_epoch:
                del self._entries[key]
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return e

    def put(self, key: tuple, known: bool, epoch: int,
            created_wall: float) -> None:
        if self.capacity <= 0:
            return
        with self._lock:
            e = self._entries.get(key)
            if e is not None and e.epoch > epoch:
                return  # never downgrade to an older view's answer
            self._entries[key] = CacheEntry(known, epoch, created_wall)
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
            size = len(self._entries)
        set_gauge("serve", "cache_size", value=float(size))

    def stats(self) -> dict:
        with self._lock:
            return {
                "cache_size": len(self._entries),
                "cache_cap": self.capacity,
                "cache_hits": self.hits,
                "cache_misses": self.misses,
            }
