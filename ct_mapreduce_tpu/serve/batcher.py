"""Deadline-driven dynamic micro-batching for the query plane.

The device ``contains`` kernels (and their vectorized host mirrors)
are batch ops: one probe of 512 lanes costs barely more than one probe
of 1 — random-access table reads are latency-priced per DISPATCH, not
per lane (tools/randacc.py). An online query plane therefore wants the
inference-serving discipline: concurrent single-key requests coalesce
into one batch, bounded by a max batch size and a max delay, with
admission control so overload sheds loudly instead of queueing without
bound.

:class:`MicroBatcher` is that loop, oracle-agnostic: callers
``submit()`` lists of opaque items and block; one worker thread
collects whatever is queued — releasing a batch as soon as
``max_batch`` lanes are waiting or ``max_delay_s`` has passed since
the OLDEST queued request — runs ``run_batch`` over the concatenation,
and scatters results back. Guarantees:

- **Bounded wait.** A request waits at most ``max_delay_s`` for its
  batch to form, plus at most one in-flight batch execution before its
  own runs (single worker, FIFO) — so p99 wait ≤ max_delay + ~2×batch
  execution, asserted from the ``serve.wait``/``serve.batch`` spans by
  the bench serve leg.
- **Bounded queue.** Admission beyond ``max_queue_lanes`` queued lanes
  raises :class:`Overloaded` immediately (the ``serve.shed`` counter);
  nothing is silently dropped and nothing queues unboundedly.
- **Deadlines.** A request whose deadline passes while it is still
  queued is failed with :class:`DeadlineExceeded` rather than running
  stale work the client already gave up on.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

from ct_mapreduce_tpu.telemetry import trace
from ct_mapreduce_tpu.telemetry.metrics import (
    add_sample,
    incr_counter,
    set_gauge,
)


class Overloaded(RuntimeError):
    """Admission queue full — the explicit load-shedding rejection."""


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed before its batch executed."""


class _Pending:
    __slots__ = ("items", "deadline", "enq_t", "done", "result", "error",
                 "trace_ctx")

    def __init__(self, items: list, deadline: Optional[float],
                 enq_t: float) -> None:
        self.items = items
        self.deadline = deadline
        self.enq_t = enq_t
        self.done = threading.Event()
        self.result: Optional[list] = None
        self.error: Optional[Exception] = None
        # Cross-process correlation (round 23): the submitter's trace
        # context crosses to the batch worker thread with the request.
        self.trace_ctx = trace.get_trace_context()


class MicroBatcher:
    """Coalesce concurrent ``submit()`` calls into bounded batches.

    ``run_batch(items) -> results`` must be length-preserving; it runs
    on the single worker thread, so an oracle that is not itself
    thread-safe needs no locking. A request of up to ``max_batch``
    items is never split across batches (its results come from one
    epoch); a bulk submission LARGER than ``max_batch`` is split into
    max_batch-sized sub-requests at admission (``serve.split_requests``)
    and reassembled in order — so oversized bulks coalesce legally with
    concurrent traffic instead of forcing one illegal oversized batch,
    at the cost that their results may span epochs (each sub-batch is
    individually epoch-consistent; callers that surface an epoch should
    report the minimum).
    """

    def __init__(
        self,
        run_batch: Callable[[list], list],
        max_batch: int = 4096,
        max_delay_s: float = 0.002,
        max_queue_lanes: int = 1 << 16,
        name: str = "serve-batcher",
    ) -> None:
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self._run_batch = run_batch
        self.max_batch = int(max_batch)
        self.max_delay_s = float(max_delay_s)
        self.max_queue_lanes = int(max_queue_lanes)
        self._cv = threading.Condition()
        self._queue: deque[_Pending] = deque()
        self._queued_lanes = 0
        self._closed = False
        self._thread = threading.Thread(
            target=self._worker, name=name, daemon=True)
        self._thread.start()

    # -- client side -----------------------------------------------------
    def submit(self, items: list, timeout_s: Optional[float] = None) -> list:
        """Run ``items`` through the oracle as part of some batch;
        blocks until the batch executes. Raises :class:`Overloaded` on
        a full admission queue and :class:`DeadlineExceeded` when
        ``timeout_s`` elapses first."""
        if not items:
            return []
        now = time.monotonic()
        n = len(items)
        with self._cv:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            if self._queued_lanes + n > self.max_queue_lanes:
                incr_counter("serve", "shed", value=float(n))
                raise Overloaded(
                    f"admission queue full ({self._queued_lanes} lanes "
                    f"queued, cap {self.max_queue_lanes}); retry later")
            deadline = None if timeout_s is None else now + timeout_s
            if n <= self.max_batch:
                parts = [_Pending(items, deadline, now)]
            else:
                # Oversized bulk: admit as max_batch-sized sub-requests
                # under this ONE admission decision (all or shed), so
                # the worker can legally coalesce and cap every batch.
                incr_counter("serve", "split_requests")
                parts = [
                    _Pending(items[i : i + self.max_batch], deadline, now)
                    for i in range(0, n, self.max_batch)
                ]
            self._queue.extend(parts)
            self._queued_lanes += n
            set_gauge("serve", "queue_lanes", value=float(self._queued_lanes))
            incr_counter("serve", "requests")
            incr_counter("serve", "lanes", value=float(n))
            self._cv.notify()
        with trace.span("serve.wait", cat="serve", lanes=n):
            for p in parts:
                p.done.wait()
        add_sample("serve", "wait_s", value=time.monotonic() - now)
        err = next((p.error for p in parts if p.error is not None), None)
        if err is not None:
            raise err
        if len(parts) == 1:
            return parts[0].result
        return [r for p in parts for r in p.result]

    def queue_lanes(self) -> int:
        with self._cv:
            return self._queued_lanes

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=5)
        # Anything still queued fails loudly rather than hanging its
        # waiter forever.
        with self._cv:
            drained = list(self._queue)
            self._queue.clear()
            self._queued_lanes = 0
        for p in drained:
            p.error = RuntimeError("MicroBatcher closed")
            p.done.set()

    # -- worker side -----------------------------------------------------
    def _collect(self) -> list:
        """Block until a batch is due, then pop it (whole requests,
        up to ``max_batch`` lanes). Returns [] on shutdown."""
        with self._cv:
            while not self._queue:
                if self._closed:
                    return []
                self._cv.wait()
            # Deadline-driven formation: release when max_batch lanes
            # are waiting, or max_delay_s after the OLDEST request
            # enqueued — whichever first. New arrivals notify. (Only
            # this worker pops, so the queue cannot empty mid-wait.)
            due = self._queue[0].enq_t + self.max_delay_s
            while self._queued_lanes < self.max_batch and not self._closed:
                remaining = due - time.monotonic()
                if remaining <= 0:
                    break
                self._cv.wait(timeout=remaining)
            batch: list[_Pending] = []
            lanes = 0
            while self._queue:
                head = self._queue[0]
                if batch and lanes + len(head.items) > self.max_batch:
                    break
                self._queue.popleft()
                batch.append(head)
                lanes += len(head.items)
            self._queued_lanes -= lanes
            set_gauge("serve", "queue_lanes", value=float(self._queued_lanes))
        return batch

    def _worker(self) -> None:
        while True:
            batch = self._collect()
            if not batch:
                if self._closed:
                    return
                continue
            now = time.monotonic()
            live = []
            for p in batch:
                if p.deadline is not None and now > p.deadline:
                    incr_counter("serve", "deadline_expired")
                    p.error = DeadlineExceeded(
                        f"deadline passed {now - p.deadline:.3f}s before "
                        "the batch executed")
                    p.done.set()
                else:
                    live.append(p)
            if not live:
                continue
            flat = [it for p in live for it in p.items]
            # Single-context batches adopt the submitter's trace ids on
            # this worker thread, so serve.batch and everything the
            # oracle nests under it correlate with the client's request;
            # a coalesced batch spanning traces stays untagged (one span
            # cannot honestly belong to several traces).
            ctxs = {p.trace_ctx for p in live if p.trace_ctx is not None}
            only = ctxs.pop() if len(ctxs) == 1 else (None,)
            try:
                with trace.trace_context(*only), \
                        trace.span("serve.batch", cat="serve",
                                   lanes=len(flat), requests=len(live)):
                    results = self._run_batch(flat)
                if len(results) != len(flat):
                    raise RuntimeError(
                        f"oracle returned {len(results)} results for "
                        f"{len(flat)} items")
            except Exception as err:
                incr_counter("serve", "batch_errors")
                for p in live:
                    p.error = err
                    p.done.set()
                continue
            incr_counter("serve", "batches")
            add_sample("serve", "batch_lanes", value=float(len(flat)))
            pos = 0
            for p in live:
                p.result = list(results[pos : pos + len(p.items)])
                pos += len(p.items)
                p.done.set()
