"""Snapshot isolation for the query plane: epoch-pinned read views.

Queries must not race ingest. The aggregator's table buffer is donated
through every device step (the previous buffer is dead after dispatch)
and its host-lane sets mutate under the fold lock, so a reader that
touched live state mid-step could see a torn table or a half-folded
batch. Instead of per-query locking, the query plane reads an
**immutable epoch-pinned view**: :func:`capture_view` takes the
aggregator's fold lock, then the table lock (the established global
order — see ``TpuAggregator.__init__``), copies the table rows to host
memory through the same one-fetch read the checkpoint writer uses, and
freezes the host-lane serial sets. Every query against that view is
lock-free and sees one consistent epoch.

Consistency contract (pinned by the threaded stress test in
tests/test_serve.py): any serial whose ingest was **acked** (its
``complete()`` returned) before the view was captured reads as known —
device-lane inserts land in the table at submit time (before the ack)
and host-lane serials fold under the fold lock the capture holds — and
a serial never fed cannot read known (membership is exact, not
probabilistic: the 128-bit fingerprint's false-positive odds are the
same ones the dedup itself already accepts).

Staleness is a bound, not an accident: :class:`SnapshotManager`
refreshes the view when it is older than ``max_staleness_s`` and every
response carries the view's epoch and age, so a consumer can tell
"known as of 0.3 s ago" from "known as of now".

:class:`ReplicaPool` (round 12) is the production tier of the same
idea: N epoch-pinned **device** views serve round-robin, refreshed
STAGGERED — one replica swaps to a new epoch at a time, captured and
pinned on a background thread — so a capture (the table D2H under the
fold/table locks, which contends with ingest) never stalls the serving
path, and serving itself runs the jitted ``contains`` kernels on
pinned device copies instead of sharing a host core with ingest's
numpy. On a mesh the pool pins **per-shard row blocks**, each on its
shard's own device (queries route by ``shard_of_np`` exactly like
ingest lanes); on one chip it pins N full copies. Mixed epochs across
replicas are safe by construction: every view is individually
consistent, answers carry the serving view's epoch + age, and
membership is monotone (a serial is never deleted), so an older
replica can only under-report within its surfaced staleness.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from ct_mapreduce_tpu.core import packing
from ct_mapreduce_tpu.ops import buckettable, hashtable
from ct_mapreduce_tpu.telemetry import trace
from ct_mapreduce_tpu.telemetry.metrics import (
    incr_counter,
    measure,
    set_gauge,
)


class TableView:
    """One immutable epoch of aggregator state, query-ready.

    ``rows`` is the host copy of the dedup table (fused layout rows for
    either table layout; for a sharded aggregator the global
    row-concatenated array, shard ``i`` owning the ``i``-th contiguous
    block). ``host_serials`` maps ``(issuer_idx, exp_hour)`` to a
    frozen set of exact-lane serial bytes. Membership is the union of
    the two domains, mirroring the aggregator's own cross-domain
    guards.
    """

    def __init__(
        self,
        epoch: int,
        rows: np.ndarray,
        layout: str,
        n_shards: int,
        max_probes: int,
        base_hour: int,
        host_serials: dict,
        issuer_totals: np.ndarray,
        crl_counts: dict,
        dn_counts: dict,
        registry,
        table_fill: int,
        capacity: int,
        device: bool = False,
        devices: Optional[list] = None,
        created_wall: Optional[float] = None,
        verify_counts: Optional[dict] = None,
    ) -> None:
        self.epoch = epoch
        self.rows = rows
        self.layout = layout
        self.n_shards = n_shards
        self.max_probes = max_probes
        self.base_hour = base_hour
        self.host_serials = host_serials
        self.issuer_totals = issuer_totals
        self.crl_counts = crl_counts
        self.dn_counts = dn_counts
        self.registry = registry
        self.table_fill = table_fill
        self.capacity = capacity
        # issuerID → (verified, failed) embedded-SCT verdicts as of
        # this epoch (round 13); empty when the verify lane is off.
        self.verify_counts = verify_counts or {}
        # Anchored at capture START (not completion): any ingest acked
        # before this instant had released the fold lock before the
        # capture acquired it, so it is provably inside the view — and
        # the surfaced staleness errs larger, never smaller.
        self.created_wall = (time.time() if created_wall is None
                             else created_wall)
        self._device = bool(device)
        self._devices = devices  # explicit placement targets (pool mode)
        self._dev_rows = None  # pinned device copy (device mode)
        self._dev_blocks = None  # per-shard pinned states (sharded pool)
        self.replica_ix = None  # pool slot this view serves from

    def age_s(self) -> float:
        return max(0.0, time.time() - self.created_wall)

    def pin(self) -> "TableView":
        """Materialize the device copy NOW, on the caller's (refresh)
        thread, so the serving path never pays the H2D transfer. In a
        sharded pool each shard's contiguous row block is placed on
        its own device — a replica never holds the full global rows on
        any one chip — wrapped as a ready probe state (rows + count on
        the SAME device, so the jitted kernel runs without cross-device
        transfers). Any failure to pin (no device, OOM, backend down)
        flips the view to the host-numpy mirror permanently — the next
        epoch's capture retries the device path."""
        if not self._device:
            return self
        try:
            import jax
            import jax.numpy as jnp

            if self.n_shards > 1 and self._devices:
                block = self.rows.shape[0] // self.n_shards
                state_cls = (buckettable.BucketTable
                             if self.layout == "bucket"
                             else hashtable.TableState)
                blocks = []
                for s in range(self.n_shards):
                    dev = self._devices[s % len(self._devices)]
                    rows = jax.device_put(
                        self.rows[s * block : (s + 1) * block], dev)
                    count = jax.device_put(np.zeros((), np.int32), dev)
                    blocks.append(state_cls(rows, count))
                self._dev_blocks = blocks
            elif self._devices:
                self._dev_rows = jax.device_put(self.rows,
                                                self._devices[0])
            else:
                self._dev_rows = jnp.asarray(self.rows)
        except Exception:
            incr_counter("serve", "device_fallback")
            self._device = False
            self._dev_rows = None
            self._dev_blocks = None
        return self

    # -- membership ------------------------------------------------------
    def contains_fps(self, fps: np.ndarray) -> np.ndarray:
        """bool[n] membership of fingerprint rows ``uint32[n, 4]``
        against the pinned table — host NumPy by default; ``device``
        views pin one device copy and run the jitted ``contains``
        kernels on pow2-padded batches (log-bounded compile shapes)."""
        n = int(len(fps))
        if n == 0 or self.rows.shape[0] == 0:
            return np.zeros((n,), bool)
        fps = np.asarray(fps, np.uint32).reshape(n, 4)
        if self._device:
            return self._contains_device(fps)
        with trace.span("serve.contains_host", cat="serve", lanes=n):
            return self._contains_host(fps)

    def _contains_host(self, fps: np.ndarray) -> np.ndarray:
        if self.n_shards == 1:
            if self.layout == "bucket":
                return buckettable.contains_np(
                    self.rows, fps, max_probes=self.max_probes)
            return hashtable.contains_np(
                self.rows, fps, max_probes=self.max_probes)
        # Sharded read view: home shard from the routing hash, then the
        # layout's local probe inside that shard's contiguous row block
        # — the exact addressing the sharded insert used to place the
        # key (one contains_np per occupied shard, not per lane).
        from ct_mapreduce_tpu.agg.sharded import shard_of_np

        dest = shard_of_np(fps, self.n_shards)
        out = np.zeros((fps.shape[0],), bool)
        block = self.rows.shape[0] // self.n_shards
        for s in np.unique(dest):
            sel = dest == s
            local = self.rows[s * block : (s + 1) * block]
            if self.layout == "bucket":
                out[sel] = buckettable.contains_np(
                    local, fps[sel], max_probes=self.max_probes)
            else:
                out[sel] = hashtable.contains_np(
                    local, fps[sel], max_probes=self.max_probes)
        return out

    def _contains_device(self, fps: np.ndarray) -> np.ndarray:
        if self._dev_rows is None and self._dev_blocks is None:
            # Pinned once per view: queries must never touch the live
            # (donated-through) table buffer. pin() flips the view to
            # the host mirror when no device copy can land.
            self.pin()
            if not self._device:
                return self._contains_host(fps)
        try:
            with trace.span("serve.contains_device", cat="serve",
                            lanes=int(fps.shape[0])):
                return self._contains_device_pinned(fps)
        except Exception:
            # A pinned copy that stops answering (device reset, backend
            # teardown mid-run) degrades to the host mirror instead of
            # failing the batch; the next epoch retries the device.
            incr_counter("serve", "device_fallback")
            self._device = False
            self._dev_rows = None
            self._dev_blocks = None
            return self._contains_host(fps)

    def _contains_device_pinned(self, fps: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp

        n = fps.shape[0]
        if self._dev_blocks is not None:
            # Shard-routed: home shard on host (the ingest routing
            # hash), then the jitted single-table probe against that
            # shard's pinned block on that shard's device.
            from ct_mapreduce_tpu.agg.sharded import shard_of_np

            dest = shard_of_np(fps, self.n_shards)
            out = np.zeros((n,), bool)
            for s in np.unique(dest):
                sel = dest == s
                out[sel] = self._probe_state(self._dev_blocks[s],
                                             fps[sel])
            return out
        width = max(16, 1 << max(0, (n - 1).bit_length()))
        if width != n:
            fps = np.pad(fps, ((0, width - n), (0, 0)))
        keys = jnp.asarray(fps)
        if self.n_shards > 1:
            from ct_mapreduce_tpu.agg import sharded

            fn = (sharded._contains_global_bucket
                  if self.layout == "bucket" else sharded._contains_global)
            found = fn(self._dev_rows, keys, n_shards=self.n_shards,
                       max_probes=self.max_probes)
        elif self.layout == "bucket":
            found = buckettable.contains(
                buckettable.BucketTable(self._dev_rows,
                                        jnp.zeros((), jnp.int32)),
                keys, max_probes=self.max_probes)
        else:
            found = hashtable.contains(
                hashtable.TableState(self._dev_rows,
                                     jnp.zeros((), jnp.int32)),
                keys, max_probes=self.max_probes)
        return np.asarray(found)[:n]

    def _probe_state(self, state, fps: np.ndarray) -> np.ndarray:
        """Jitted contains against one pinned probe state, pow2-padded
        (min 16) so compile shapes stay log-bounded — the same rule as
        the aggregator's `_device_contains`. Keys are placed on the
        state's device so the kernel never crosses chips."""
        import jax

        n = fps.shape[0]
        width = max(16, 1 << max(0, (n - 1).bit_length()))
        if width != n:
            fps = np.pad(fps, ((0, width - n), (0, 0)))
        dev = next(iter(state.rows.devices()), None)
        keys = jax.device_put(fps, dev)
        fn = (buckettable.contains if self.layout == "bucket"
              else hashtable.contains)
        return np.asarray(fn(state, keys, max_probes=self.max_probes))[:n]

    def lookup(self, items: list) -> np.ndarray:
        """Batch membership: ``items`` is a list of
        ``(issuer_idx, exp_hour, serial_bytes)`` (``issuer_idx`` may be
        ``-1`` for an issuer the registry has never seen). Returns
        bool[n]: known in EITHER dedup domain.

        Device-eligible lanes (serial fits the fingerprint window,
        issuer/hour in meta range — the same predicates that routed
        them to the device at ingest) probe the pinned table through
        the vectorized host fingerprint; every lane additionally checks
        the frozen host-lane set, because overflow/boundary routing
        means the domains can overlap (aggregator module docstring).
        """
        n = len(items)
        out = np.zeros((n,), bool)
        if n == 0:
            return out
        idx = np.fromiter((it[0] for it in items), np.int64, n)
        eh = np.fromiter((it[1] for it in items), np.int64, n)
        slen = np.fromiter((len(it[2]) for it in items), np.int64, n)
        eligible = (
            (idx >= 0)
            & (idx < packing.MAX_ISSUERS)
            & (slen <= packing.MAX_SERIAL_BYTES)
            & (eh - self.base_hour >= 0)
            & (eh - self.base_hour < packing.META_HOUR_SPAN)
        )
        sel = np.nonzero(eligible)[0]
        if sel.size:
            serials = np.zeros((sel.size, packing.MAX_SERIAL_BYTES), np.uint8)
            for j, p in enumerate(sel):
                sb = items[p][2]
                serials[j, : len(sb)] = np.frombuffer(sb, np.uint8)
            fps = packing.fingerprints_np(
                idx[sel], eh[sel], serials, slen[sel])
            out[sel] = self.contains_fps(fps)
        if self.host_serials:
            for p in range(n):
                if not out[p]:
                    bucket = self.host_serials.get((int(idx[p]), int(eh[p])))
                    if bucket is not None and items[p][2] in bucket:
                        out[p] = True
        return out

    # -- metadata --------------------------------------------------------
    def issuer_meta(self, issuer_id: str) -> Optional[dict]:
        """Per-issuer metadata as of this epoch, or None when the
        registry has never seen the issuer."""
        idx = self.registry.index_of_issuer_id(issuer_id)
        if idx is None:
            return None
        total = (int(self.issuer_totals[idx])
                 if idx < self.issuer_totals.shape[0] else 0)
        meta = {
            "issuer": issuer_id,
            "unknown_total": total,
            "crls": int(self.crl_counts.get(idx, 0)),
            "dns": int(self.dn_counts.get(idx, 0)),
        }
        vc = self.verify_counts.get(issuer_id)
        if vc is not None:
            meta["verified"], meta["failed"] = int(vc[0]), int(vc[1])
        return meta


def capture_view(agg, epoch: int, device: bool = False,
                 devices: Optional[list] = None) -> TableView:
    """Pin one epoch of ``agg`` (TpuAggregator, ShardedAggregator, or
    the host snapshot reader) into an immutable :class:`TableView`.

    Lock order is fold → table, matching every other cross-state reader
    (``grow``, ``drain``): holding the fold lock freezes the host-lane
    sets mid-nothing (folds serialize on it), and the table lock
    guarantees the row fetch reads a live, fully-stepped buffer. The
    row read is the checkpoint writer's one-fetch idiom
    (``_write_npz``): a single D2H of ``table.rows`` rather than
    per-field property reads."""
    t0 = time.time()
    with agg._fold_lock:
        with agg._table_lock:
            dedup = getattr(agg, "dedup", None)
            if dedup is not None:  # mesh-sharded: global row view
                rows = np.asarray(dedup.rows)
                layout = dedup.layout
                n_shards = dedup.n_shards
            else:
                layout = ("bucket"
                          if isinstance(agg.table, buckettable.BucketTable)
                          else "open")
                rows = np.asarray(agg.table.rows)
                n_shards = 1
        host_serials = {k: frozenset(v)
                        for k, v in agg.host_serials.items() if v}
        issuer_totals = agg.issuer_totals.copy()
        crl_counts = {i: len(s) for i, s in agg.crl_sets.items()}
        dn_counts = {i: len(s) for i, s in agg.dn_sets.items()}
        verify_counts = agg.verify_counts()
        table_fill = agg._table_fill
    return TableView(
        epoch=epoch, rows=rows, layout=layout, n_shards=n_shards,
        max_probes=agg.max_probes, base_hour=agg.base_hour,
        host_serials=host_serials, issuer_totals=issuer_totals,
        crl_counts=crl_counts, dn_counts=dn_counts, registry=agg.registry,
        table_fill=table_fill,
        capacity=getattr(agg, "capacity", rows.shape[0]),
        device=device,
        devices=devices,
        created_wall=t0,
        verify_counts=verify_counts,
    )


class SnapshotManager:
    """Bounded-staleness view cache: ``view()`` returns the current
    epoch, refreshing (at most one capture in flight — concurrent
    requesters coalesce on the losing side of the lock) once the view
    is older than ``max_staleness_s``. ``refresh()`` forces a new
    epoch, e.g. after a checkpoint restore."""

    def __init__(self, agg, max_staleness_s: float = 1.0,
                 device: bool = False) -> None:
        self._agg = agg
        self.max_staleness_s = float(max_staleness_s)
        self._device = bool(device)
        self._lock = threading.Lock()
        self._view: Optional[TableView] = None
        self._epoch = 0
        self._refreshing = False

    @property
    def refresh_in_flight(self) -> bool:
        """True while a capture is running — readers that raced past
        the staleness check are being served the previous view for the
        capture's full duration, so staleness can transiently exceed
        the bound; this flag (surfaced in stats()/healthz) plus the
        ``serve.snapshot_age_s`` gauge make that window observable."""
        return self._refreshing

    def view(self) -> TableView:
        v = self._view
        if v is not None and v.age_s() <= self.max_staleness_s:
            set_gauge("serve", "snapshot_age_s", value=v.age_s())
            return v
        with self._lock:
            v = self._view  # a concurrent refresher may have won
            if v is not None and v.age_s() <= self.max_staleness_s:
                return v
            return self._refresh_locked()

    def refresh(self) -> TableView:
        with self._lock:
            return self._refresh_locked()

    def _refresh_locked(self) -> TableView:
        self._epoch += 1
        self._refreshing = True
        try:
            with trace.span("serve.snapshot", cat="serve",
                            epoch=self._epoch), \
                    measure("serve", "snapshot_capture_s"):
                v = capture_view(self._agg, self._epoch,
                                 device=self._device)
        finally:
            self._refreshing = False
        self._view = v
        incr_counter("serve", "snapshot_refresh")
        set_gauge("serve", "snapshot_epoch", value=float(self._epoch))
        set_gauge("serve", "snapshot_age_s", value=v.age_s())
        return v

    def stats(self) -> dict:
        v = self._view
        return {
            "snapshot_epoch": v.epoch if v else 0,
            "snapshot_age_s": round(v.age_s(), 6) if v else None,
            "refresh_in_flight": self._refreshing,
        }


class ReplicaPool:
    """N epoch-pinned device views serving round-robin with STAGGERED
    refresh — the query plane's answer to "serve and ingest share a
    core" (BENCHLOG round 10).

    Every replica is a full, individually consistent :class:`TableView`
    pinned on device at capture time (``pin()`` runs on the refresh
    thread, never the serving path). ``view()`` hands out replicas
    round-robin; when the STALEST replica outlives ``max_staleness_s``
    (or the pool is not yet full), one background capture swaps that
    single replica to a fresh epoch — one at a time, so the D2H +
    fold/table-lock cost of a capture is paid off the serving path and
    at most one capture contends with ingest at any moment.

    Mixed epochs across replicas are part of the contract, not a race:
    a batch is answered entirely by one replica, carries that replica's
    epoch + age, and membership is monotone — an older replica can only
    under-report within the staleness it surfaces. ``floor_epoch()``
    (the minimum live epoch) is the validity horizon the hot-serial
    cache keys against.

    Placement: on a mesh-sharded aggregator each replica pins one
    per-shard row block per device (``TableView.pin``'s shard-routed
    mode) so no chip ever holds the full global rows; on one chip the
    pool holds N full pinned copies. ``device=False`` degrades every
    replica to the host-numpy mirror (and any pin failure does the
    same per view, loudly, via ``serve.device_fallback``)."""

    def __init__(self, agg, n_replicas: int = 2,
                 max_staleness_s: float = 1.0, device: bool = True,
                 devices: Optional[list] = None) -> None:
        self._agg = agg
        self.n_replicas = max(1, int(n_replicas))
        self.max_staleness_s = float(max_staleness_s)
        self._device = bool(device)
        self._devices = devices
        self._lock = threading.Lock()  # replica list + counters
        self._refresh_lock = threading.Lock()  # one capture at a time
        self._replicas: list[TableView] = []
        self._rr = 0
        self._epoch = 0
        self._refreshing = False

    @property
    def refresh_in_flight(self) -> bool:
        return self._refreshing

    def _resolve_devices(self) -> Optional[list]:
        if self._devices is None and self._device:
            try:
                import jax

                self._devices = list(jax.devices())
            except Exception:
                self._devices = []
        return self._devices or None

    def _capture(self) -> TableView:
        with self._lock:
            self._epoch += 1
            epoch = self._epoch
        with trace.span("serve.snapshot", cat="serve", epoch=epoch), \
                measure("serve", "replica_swap_s"):
            v = capture_view(self._agg, epoch, device=self._device,
                             devices=self._resolve_devices())
            v.pin()  # transfer on THIS thread, not the serving path
        return v

    def _adopt(self, v: TableView) -> None:
        with self._lock:
            if len(self._replicas) < self.n_replicas:
                v.replica_ix = len(self._replicas)
                self._replicas.append(v)
            else:
                stale = min(range(len(self._replicas)),
                            key=lambda i: self._replicas[i].epoch)
                v.replica_ix = stale
                self._replicas[stale] = v
            n = len(self._replicas)
        incr_counter("serve", "replica_refresh")
        set_gauge("serve", "replicas", value=float(n))
        set_gauge("serve", "snapshot_epoch", value=float(v.epoch))

    def _refresh_holding_lock(self) -> TableView:
        self._refreshing = True
        try:
            v = self._capture()
            self._adopt(v)
            return v
        finally:
            self._refreshing = False

    def refresh(self) -> TableView:
        """Force one staggered swap NOW (synchronous): capture + pin a
        new epoch and replace the stalest replica (or fill an empty
        pool slot). Serving continues on the other replicas meanwhile."""
        with self._refresh_lock:
            return self._refresh_holding_lock()

    def warm(self) -> "ReplicaPool":
        """Fill every pool slot synchronously (bench/sweep setup, so
        the timed window never includes a capture)."""
        while True:
            with self._lock:
                if len(self._replicas) >= self.n_replicas:
                    return self
            self.refresh()

    def view(self) -> TableView:
        """One replica, round-robin; triggers a background staggered
        swap when the stalest replica is past the staleness bound. Only
        the very first call (empty pool) captures synchronously."""
        with self._lock:
            reps = list(self._replicas)
            if reps:
                self._rr = (self._rr + 1) % len(reps)
                v = reps[self._rr]
        if not reps:
            with self._refresh_lock:
                with self._lock:
                    if self._replicas:  # lost the first-capture race
                        return self._replicas[0]
                return self._refresh_holding_lock()
        due = (len(reps) < self.n_replicas
               or max(r.age_s() for r in reps) > self.max_staleness_s)
        if due and not self._refreshing:
            self._refresh_async()
        set_gauge("serve", "snapshot_age_s", value=v.age_s())
        return v

    def _refresh_async(self) -> None:
        if not self._refresh_lock.acquire(blocking=False):
            return  # a capture is already in flight
        self._refreshing = True

        def run() -> None:
            try:
                v = self._capture()
                self._adopt(v)
            finally:
                self._refreshing = False
                self._refresh_lock.release()

        threading.Thread(target=run, name="serve-replica-refresh",
                         daemon=True).start()

    def floor_epoch(self) -> int:
        """Minimum epoch across live replicas — the oldest answer the
        round-robin could legally serve, and the hot-serial cache's
        validity horizon."""
        with self._lock:
            return min((r.epoch for r in self._replicas), default=0)

    def stats(self) -> dict:
        with self._lock:
            reps = list(self._replicas)
            refreshing = self._refreshing
        ages = [round(r.age_s(), 6) for r in reps]
        return {
            "replicas": len(reps),
            "replica_target": self.n_replicas,
            "replica_epochs": [r.epoch for r in reps],
            "replica_ages_s": ages,
            "replica_device": [bool(r._device) for r in reps],
            "snapshot_epoch": max((r.epoch for r in reps), default=0),
            "snapshot_age_s": min(ages) if ages else None,
            "refresh_in_flight": refreshing,
        }
