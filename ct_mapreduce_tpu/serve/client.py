"""Client for the query plane (``serve/server.py``) — the transport
behind the ``ct-query`` binary and ``ct-getcert``'s ``queryPort``
routing. Stdlib-only (urllib), no streaming: requests are small JSON
documents by design (the batching happens server-side).

Round 23 cross-process correlation: every request mints a
W3C-traceparent-style header (telemetry/trace.py) and wraps itself in
a ``query.client`` span carrying the same trace_id — the server side
extracts the header and tags its serve spans with it, so
``traceview --merge`` shows one request crossing both processes."""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Optional

from ct_mapreduce_tpu.telemetry import trace


class QueryError(RuntimeError):
    """Non-2xx answer from the query plane (status + decoded body)."""

    def __init__(self, status: int, body: dict):
        self.status = status
        self.body = body
        super().__init__(f"query plane returned {status}: "
                         f"{body.get('error', body)}")


class QueryClient:
    """Thin HTTP client: ``addr`` is ``host:port``, ``:port`` (=
    localhost), or a full ``http://...`` base URL."""

    def __init__(self, addr: str, timeout_s: float = 10.0) -> None:
        if addr.startswith(("http://", "https://")):
            base = addr
        else:
            if addr.startswith(":"):
                addr = "127.0.0.1" + addr
            base = f"http://{addr}"
        self.base_url = base.rstrip("/")
        self.timeout_s = timeout_s

    def _request(self, path: str, payload: Optional[dict] = None) -> dict:
        url = self.base_url + path
        data = None
        header, trace_id, span_id = trace.mint_traceparent()
        headers = {trace.TRACEPARENT_HEADER: header}
        if payload is not None:
            data = json.dumps(payload).encode()
            headers["Content-Type"] = "application/json"
        req = urllib.request.Request(url, data=data, headers=headers)
        with trace.trace_context(trace_id, span_id), \
                trace.span("query.client", "serve", path=path):
            try:
                with urllib.request.urlopen(
                        req, timeout=self.timeout_s) as resp:
                    return json.loads(resp.read().decode())
            except urllib.error.HTTPError as err:
                try:
                    body = json.loads(err.read().decode())
                except (ValueError, OSError):
                    body = {"error": str(err)}
                raise QueryError(err.code, body) from None

    def query(self, queries: list[dict],
              timeout_ms: Optional[int] = None) -> dict:
        """Bulk membership: ``queries`` is a list of
        ``{"issuer", "expDate", "serial"}`` dicts; returns the server's
        response (``results`` + ``epoch`` + ``staleness_s``)."""
        payload: dict = {"queries": queries}
        if timeout_ms is not None:
            payload["timeoutMs"] = timeout_ms
        return self._request("/query", payload)

    def query_one(self, issuer: str, exp_date: str, serial_hex: str,
                  timeout_ms: Optional[int] = None) -> dict:
        payload: dict = {"issuer": issuer, "expDate": exp_date,
                         "serial": serial_hex}
        if timeout_ms is not None:
            payload["timeoutMs"] = timeout_ms
        return self._request("/query", payload)

    def issuer(self, issuer_id: str) -> dict:
        from urllib.parse import quote

        return self._request(f"/issuer/{quote(issuer_id, safe='')}")

    def healthz(self) -> dict:
        return self._request("/healthz")

    def getcert(self, log_url: str, index: int) -> str:
        """PEM of one log entry via the serving-plane proxy."""
        from urllib.parse import urlencode

        qs = urlencode({"log": log_url, "index": int(index)})
        return self._request(f"/getcert?{qs}")["pem"]
