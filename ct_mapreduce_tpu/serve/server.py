"""The query plane's HTTP surface: a batched membership oracle as a
stdlib JSON API (``queryPort`` directive).

Endpoints:

- ``POST /query`` — one or many membership questions. Body is either a
  single query object or ``{"queries": [...]}``; each query is
  ``{"issuer": <issuerID>, "expDate": <expDate id>, "serial": <hex>}``
  (issuerID = base64url(SHA-256(SPKI)), expDate in the report formats
  ``2031-06-15`` / ``2031-06-15-14``, serial as hex content bytes).
  Optional ``"timeoutMs"`` is the request deadline. The response
  carries per-query ``known`` flags plus the answering view's
  ``epoch`` and ``staleness_s`` — a consumer always knows HOW current
  the answer is. Overload is an explicit ``429 overloaded``; a missed
  deadline is ``504 deadline_exceeded``.
- ``GET /issuer/<issuerID>`` — per-issuer metadata (running unknown
  total, CRL/DN set sizes) from the same pinned view.
- ``GET /healthz`` — queue depth vs cap, snapshot age/epoch, shed
  total: the numbers that distinguish "keeping up" from "shedding".
- ``GET /getcert?log=<url>&index=<n>`` — serving-plane proxy for the
  ``ct-getcert`` flow: the server (which already holds log
  credentials/limits) fetches one entry and returns its PEM, so edge
  clients need no direct log access.

The oracle half (:class:`MembershipOracle`) is independent of HTTP —
the bench serve leg and tests drive it in-process — and composes the
three serving primitives, hottest first:
:class:`~ct_mapreduce_tpu.serve.cache.HotSerialCache` (memoized
answers, epoch-floor validated), :class:`~ct_mapreduce_tpu.serve.
batcher.MicroBatcher` (dynamic batching + admission control), and
:class:`~ct_mapreduce_tpu.serve.snapshot.ReplicaPool` (round-robin
epoch-pinned device views with staggered refresh and automatic host
fallback). ``serveReplicas`` / ``serveDevice`` / ``serveCacheSize``
directives (and their ``CTMR_SERVE_*`` env equivalents) tune the tier.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from ct_mapreduce_tpu.config import profile as platprofile
from ct_mapreduce_tpu.core.types import ExpDate
from ct_mapreduce_tpu.serve.batcher import (
    DeadlineExceeded,
    MicroBatcher,
    Overloaded,
)
from ct_mapreduce_tpu.serve.cache import HotSerialCache
from ct_mapreduce_tpu.serve.snapshot import ReplicaPool
from ct_mapreduce_tpu.telemetry import trace
from ct_mapreduce_tpu.telemetry.metrics import incr_counter


_SERVE_KNOBS = (
    platprofile.Knob("serveReplicas", "CTMR_SERVE_REPLICAS", 2,
                     parse=int, is_set=platprofile.pos_int,
                     post=lambda v: int(v)),
    platprofile.Knob("serveDevice", "CTMR_SERVE_DEVICE", True,
                     parse=platprofile.parse_bool_lenient,
                     env_is_set=platprofile.any_set, post=bool),
    platprofile.Knob("serveCacheSize", "CTMR_SERVE_CACHE_SIZE", 4096,
                     parse=int, is_set=platprofile.nonzero_int,
                     post=lambda v: max(0, int(v))),
)


def resolve_serve(replicas: int = 0, device: Optional[bool] = None,
                  cache_size: int = 0) -> tuple[int, bool, int]:
    """Resolve the serving-tier knobs through the shared
    platformProfile ladder (config/profile.py): explicit value (config
    directive / kwarg) > ``CTMR_SERVE_REPLICAS`` /
    ``CTMR_SERVE_DEVICE`` / ``CTMR_SERVE_CACHE_SIZE`` env > profile
    ``knobs.serve`` > defaults (2 replicas; device serving with
    automatic host fallback; 4096-entry hot-serial cache).
    ``cache_size < 0`` disables the cache; unparseable env values are
    ignored, matching the config layer's tolerance."""
    r = platprofile.resolve_section("serve", _SERVE_KNOBS, {
        "serveReplicas": int(replicas or 0),
        "serveDevice": device,
        "serveCacheSize": int(cache_size or 0),
    })
    return (r["serveReplicas"], r["serveDevice"], r["serveCacheSize"])


def resolve_filter_first(flag=None) -> bool:
    """Serve-plane filter-first tier: explicit value > the
    ``CTMR_SERVE_FILTER_FIRST`` env > off."""
    if flag is not None:
        return bool(flag)
    return os.environ.get("CTMR_SERVE_FILTER_FIRST", "").strip().lower() \
        in ("1", "t", "true")


class FilterTier:
    """An epoch-tagged filter-cascade snapshot in front of the table
    tier (round 15): compiled from the aggregator's filter capture,
    it answers NEGATIVE lookups without touching a table view — exact
    for every serial the build-time state knew — and forwards
    positives to the table-confirm tier, which kills the cascade's
    false positives. Serials first seen AFTER the build answer through
    the same epoch/staleness surface the replica pool already reports:
    the tier's epoch is the pool's floor epoch at build time, and
    consumers read ``staleness_s`` exactly as they do for views."""

    def __init__(self, artifact, issuer_ids: list[str], epoch: int):
        self.artifact = artifact
        # Registry snapshot: run-local issuer index → issuerID, as of
        # the build. Queries for indices past this snapshot (issuers
        # first seen after the build) must FORWARD to the table, not
        # answer negative from a filter that predates them.
        self.issuer_ids = issuer_ids
        self.epoch = int(epoch)
        self.created_wall = time.time()

    @classmethod
    def build(cls, agg, fp_rate: float, epoch: int,
              cache=None) -> "FilterTier":
        """``cache`` (a :class:`filter.cache.GroupBuildCache`) arms the
        CTMRFL02 dirty-group path: across refresh ticks only churned
        groups rebuild (the oracle owns one cache for its lifetime)."""
        from ct_mapreduce_tpu.filter import build_from_aggregator

        art = build_from_aggregator(agg, fp_rate=fp_rate, cache=cache)
        ids = [agg.registry.issuer_at(i).id()
               for i in range(len(agg.registry))]
        return cls(art, ids, epoch)

    def age_s(self) -> float:
        return max(0.0, time.time() - self.created_wall)

    def negatives(self, items: list) -> np.ndarray:
        """bool[n]: lanes the cascade answers *excluded* — definitely
        unknown as of the build. False means forward to the table
        (cascade-positive, or outside the build's registry snapshot)."""
        n = len(items)
        out = np.zeros((n,), bool)
        by_group: dict = {}
        for i, (idx, eh, _sb) in enumerate(items):
            if 0 <= int(idx) < len(self.issuer_ids):
                key = (self.issuer_ids[int(idx)], int(eh))
                by_group.setdefault(key, []).append(i)
            # idx == -1 (registry never saw the issuer): the TABLE is
            # the authority on honest-false; forward.
        with trace.span("serve.filter", cat="serve", lanes=n):
            for (iss, eh), lanes in by_group.items():
                g = self.artifact.group_for(iss, eh)
                if g is None:
                    # No serials for this (issuer, expDate) at build
                    # time: exact-negative for the build corpus.
                    out[lanes] = True
                    continue
                hit = self.artifact.query_group(
                    g, [items[i][2] for i in lanes])
                out[np.asarray(lanes)[~hit]] = True
        return out


class MembershipOracle:
    """Batched "is serial S known for (issuer, expDate)?" over a live
    aggregator: a hot-serial result cache in front of dynamic batching
    in front of a round-robin pool of epoch-pinned device replicas
    (host-numpy fallback when no device copy can pin). With
    ``filter_first`` (round 15), a filter-cascade tier sits between
    the cache and the batcher: cascade-negative lanes answer without a
    table view, cascade-positive lanes fall through for table
    confirmation."""

    def __init__(
        self,
        agg,
        max_batch: int = 4096,
        max_delay_s: float = 0.002,
        max_queue_lanes: int = 1 << 16,
        max_staleness_s: float = 1.0,
        device: Optional[bool] = None,
        replicas: int = 0,
        cache_size: int = 0,
        filter_first: Optional[bool] = None,
        filter_fp_rate: float = 0.0,
        distrib_history: int = 0,
        max_delta_chain: int = 0,
    ) -> None:
        self._agg = agg
        replicas, device, cache_size = resolve_serve(
            replicas, device, cache_size)
        self.snapshots = ReplicaPool(
            agg, n_replicas=replicas, max_staleness_s=max_staleness_s,
            device=device)
        self.cache = (HotSerialCache(cache_size)
                      if cache_size > 0 else None)
        self.batcher = MicroBatcher(
            self._run_batch, max_batch=max_batch, max_delay_s=max_delay_s,
            max_queue_lanes=max_queue_lanes)
        # Filter-first tier (round 15): built lazily on the first
        # refresh (construction must not fail when the aggregator has
        # no capture yet — the tier simply stays cold and every lane
        # takes the table path).
        from ct_mapreduce_tpu.filter import DEFAULT_FP_RATE

        self.filter_first = resolve_filter_first(filter_first)
        self.filter_fp_rate = float(filter_fp_rate) or DEFAULT_FP_RATE
        self.filter_tier: Optional[FilterTier] = None
        # Epoch-persistent build cache (CTMRFL02): refresh ticks reuse
        # clean groups' cascades verbatim, so the steady-state refresh
        # costs O(churn). Harmless for fl01 (the builder ignores it).
        from ct_mapreduce_tpu.filter import GroupBuildCache

        self.filter_build_cache = GroupBuildCache()
        # Distribution store (round 18): published epochs, delta
        # links, containers, pre-compressed variants — what the
        # /filter* CDN routes serve. Armed alongside the filter tier.
        self.distributor = None
        if self.filter_first:
            from ct_mapreduce_tpu.distrib import (
                FilterDistributor,
                resolve_distrib,
            )

            history, max_chain = resolve_distrib(
                distrib_history, max_delta_chain)
            self.distributor = FilterDistributor(
                history=history, max_chain=max_chain)
        if self.filter_first and getattr(
                agg, "filter_capture", None) is not None:
            try:
                self.refresh_filter()
            except Exception:
                pass  # serve must come up; refresh_filter can retry

    def refresh_filter(self, fp_rate: float = 0.0) -> FilterTier:
        """(Re)build the filter tier from the live aggregator's
        capture, tagged with the replica pool's current floor epoch.
        The rebuilt artifact also publishes into the distribution
        store (source ``local`` — a leader-fed merged artifact
        outranks it). Raises ``ValueError`` when the aggregator has no
        capture."""
        tier = FilterTier.build(
            self._agg, float(fp_rate) or self.filter_fp_rate,
            self.snapshots.floor_epoch(),
            cache=self.filter_build_cache)
        self.filter_tier = tier
        if self.distributor is not None:
            self.distributor.publish(
                tier.epoch, tier.artifact.to_bytes(), source="local")
        incr_counter("serve", "filter_refresh")
        return tier

    def publish_artifact(self, epoch: int, blob: bytes,
                         source: str = "fleet") -> bool:
        """Publish externally built artifact bytes (the fleet leader's
        merged filter, fanned out on epoch ticks) into this worker's
        distribution store. Byte-identical input on every worker ⇒
        identical ETags/deltas/containers fleet-wide."""
        if self.distributor is None:
            return False
        return self.distributor.publish(epoch, blob, source=source)

    def _run_batch(self, items: list) -> list:
        view = self.snapshots.view()
        with trace.span(
                "serve.lookup", cat="serve", lanes=len(items),
                epoch=view.epoch, device=int(view._device),
                replica=(-1 if view.replica_ix is None
                         else int(view.replica_ix))):
            known = view.lookup(items)
        age = view.age_s()
        return [(bool(k), view.epoch, age) for k in known]

    def query_raw(self, items: list,
                  timeout_s: Optional[float] = None) -> list:
        """items: [(issuer_idx, exp_hour, serial_bytes)] →
        [(known, epoch, staleness_s)]. Cache hits answer immediately
        (valid while their epoch >= the pool's floor — equivalent to
        the round-robin picking the stalest replica); cache misses
        consult the filter tier when armed (cascade-negative lanes
        answer at the tier's epoch, no table view touched); the rest
        batch through the oracle, each sub-batch answered by ONE
        pinned view."""
        n = len(items)
        out: list = [None] * n
        if self.cache is None:
            miss = list(range(n))
        else:
            floor = self.snapshots.floor_epoch()
            now = time.time()
            miss = []
            for i, it in enumerate(items):
                e = self.cache.get(it, floor)
                if e is None:
                    miss.append(i)
                else:
                    out[i] = (e.known, e.epoch,
                              max(0.0, now - e.created_wall))
            if n - len(miss):
                incr_counter("serve", "cache_hit",
                             value=float(n - len(miss)))
            if not miss:
                return out
            incr_counter("serve", "cache_miss", value=float(len(miss)))
        tier = self.filter_tier if self.filter_first else None
        if tier is not None and miss:
            neg = tier.negatives([items[i] for i in miss])
            age = tier.age_s()
            fwd = []
            for j, i in enumerate(miss):
                if neg[j]:
                    out[i] = (False, tier.epoch, age)
                else:
                    fwd.append(i)
            if len(miss) - len(fwd):
                incr_counter("serve", "filter_negative",
                             value=float(len(miss) - len(fwd)))
            if fwd:
                incr_counter("serve", "filter_forward",
                             value=float(len(fwd)))
            miss = fwd
        if not miss:
            return out
        res = self.batcher.submit([items[i] for i in miss],
                                  timeout_s=timeout_s)
        done = time.time()
        for i, r in zip(miss, res):
            out[i] = r
            if self.cache is not None:
                self.cache.put(items[i], known=r[0], epoch=r[1],
                               created_wall=done - r[2])
        return out

    def resolve_issuer(self, issuer_id: str) -> int:
        idx = self._agg.registry.index_of_issuer_id(issuer_id)
        return -1 if idx is None else idx

    def issuer_meta(self, issuer_id: str) -> Optional[dict]:
        view = self.snapshots.view()
        meta = view.issuer_meta(issuer_id)
        if meta is not None:
            meta["epoch"] = view.epoch
            meta["staleness_s"] = round(view.age_s(), 6)
        return meta

    def stats(self) -> dict:
        body = {
            "queue_lanes": self.batcher.queue_lanes(),
            "queue_cap": self.batcher.max_queue_lanes,
            "max_batch": self.batcher.max_batch,
            "max_delay_s": self.batcher.max_delay_s,
        }
        body.update(self.snapshots.stats())
        if self.cache is not None:
            body.update(self.cache.stats())
        body["filter_first"] = bool(self.filter_first)
        if self.filter_tier is not None:
            body["filter_epoch"] = self.filter_tier.epoch
            body["filter_staleness_s"] = round(self.filter_tier.age_s(), 6)
            body["filter_serials"] = self.filter_tier.artifact.n_serials
            body["filter_format"] = self.filter_tier.artifact.fmt
            body["filter_groups_reused"] = self.filter_build_cache.hits
        if self.distributor is not None:
            body.update(self.distributor.stats())
        return body

    def close(self) -> None:
        self.batcher.close()


def _parse_query(q: dict, oracle: MembershipOracle):
    """One JSON query object → (issuer_idx, exp_hour, serial_bytes).

    Unknown issuers map to idx -1: the lookup treats them as
    device-ineligible and the host-set probe can't match either, so
    the answer is an honest ``known: false`` (the table has, by
    definition, never counted a serial for an issuer the registry has
    never seen)."""
    issuer = q.get("issuer")
    exp = q.get("expDate")
    serial_hex = q.get("serial")
    if not isinstance(issuer, str) or not isinstance(exp, str) \
            or not isinstance(serial_hex, str):
        raise ValueError("query needs string issuer, expDate, serial")
    try:
        serial = bytes.fromhex(serial_hex)
    except ValueError as err:
        raise ValueError(f"serial is not hex: {err}") from None
    try:
        eh = ExpDate.parse(exp).unix_hour()
    except ValueError as err:
        raise ValueError(f"bad expDate {exp!r}: {err}") from None
    return (oracle.resolve_issuer(issuer), eh, serial)


class QueryServer:
    """Background HTTP server for the query plane (``queryPort``).

    Mirrors :class:`~ct_mapreduce_tpu.telemetry.promhttp.MetricsServer`
    mechanics: ``ThreadingHTTPServer`` on a daemon thread, port 0 binds
    ephemeral (tests), ``stop()`` shuts down cleanly. ``transport``
    overrides the CT-log HTTP transport for the ``/getcert`` proxy
    (tests route it at an in-process fake log)."""

    def __init__(self, agg, port: int, host: str = "0.0.0.0",
                 max_batch: int = 4096, max_delay_s: float = 0.002,
                 max_queue_lanes: int = 1 << 16,
                 max_staleness_s: float = 1.0,
                 device: Optional[bool] = None, replicas: int = 0,
                 cache_size: int = 0, transport=None,
                 filter_first: Optional[bool] = None,
                 filter_fp_rate: float = 0.0,
                 distrib_history: int = 0,
                 max_delta_chain: int = 0) -> None:
        self.host = host
        self.port = int(port)
        self.oracle = MembershipOracle(
            agg, max_batch=max_batch, max_delay_s=max_delay_s,
            max_queue_lanes=max_queue_lanes,
            max_staleness_s=max_staleness_s, device=device,
            replicas=replicas, cache_size=cache_size,
            filter_first=filter_first, filter_fp_rate=filter_fp_rate,
            distrib_history=distrib_history,
            max_delta_chain=max_delta_chain)
        self._transport = transport
        # Optional SLO probe (round 23): a callable returning the
        # current breach-reason list; non-empty flips /healthz to 503.
        self.slo_check = None
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- request handling ------------------------------------------------
    def handle_query(self, body: dict) -> tuple[int, dict]:
        queries = body.get("queries")
        single = queries is None
        if single:
            queries = [body]
        if not isinstance(queries, list) or not queries:
            return 400, {"error": "queries must be a non-empty list"}
        try:
            items = [_parse_query(q, self.oracle) for q in queries]
        except (ValueError, AttributeError, TypeError) as err:
            return 400, {"error": str(err)}
        timeout_ms = body.get("timeoutMs")
        timeout_s = float(timeout_ms) / 1e3 if timeout_ms else None
        try:
            results = self.oracle.query_raw(items, timeout_s=timeout_s)
        except Overloaded as err:
            return 429, {"error": "overloaded", "detail": str(err)}
        except DeadlineExceeded as err:
            return 504, {"error": "deadline_exceeded", "detail": str(err)}
        # A result row comes from one pinned view, but rows can span
        # views (cache hits at older epochs; oversized bulks split into
        # sub-batches) — report the OLDEST epoch consulted and the
        # LARGEST staleness, so the surfaced bound errs conservative.
        epoch = min(r[1] for r in results)
        staleness = max(r[2] for r in results)
        out = {
            "results": [{"known": known} for known, _, _ in results],
            "epoch": epoch,
            "staleness_s": round(staleness, 6),
        }
        if single:
            out["known"] = out["results"][0]["known"]
        return 200, out

    def handle_issuer(self, issuer_id: str) -> tuple[int, dict]:
        meta = self.oracle.issuer_meta(issuer_id)
        if meta is None:
            return 404, {"error": "unknown issuer", "issuer": issuer_id}
        return 200, meta

    # Cache policies per distribution resource: "latest"-shaped
    # resources move every epoch, epoch-pinned resources never change.
    _CC_LATEST = "public, max-age=60, must-revalidate"
    _CC_IMMUTABLE = "public, max-age=31536000, immutable"

    def _blob_response(self, blob: bytes, etag: str, req_headers,
                       cache_control: str, cache_key=None,
                       created_wall: Optional[float] = None,
                       epoch: Optional[int] = None):
        """One distribution payload: strong-ETag conditional GET
        (If-None-Match ⇒ 304 with zero body bytes), Accept-Encoding
        negotiation against the pre-compressed cache, and per-artifact
        cache headers."""
        from email.utils import formatdate

        headers = {"ETag": etag, "Cache-Control": cache_control,
                   "Vary": "Accept-Encoding"}
        if created_wall is not None:
            headers["Last-Modified"] = formatdate(created_wall,
                                                  usegmt=True)
        if epoch is not None:
            headers["X-Filter-Epoch"] = str(epoch)
        inm = (req_headers.get("If-None-Match", "")
               if req_headers else "")
        if inm and (inm.strip() == "*"
                    or etag in [t.strip() for t in inm.split(",")]):
            incr_counter("distrib", "http_304")
            return 304, b"", headers
        distributor = self.oracle.distributor
        if req_headers is not None and distributor is not None:
            from ct_mapreduce_tpu.distrib import negotiate_encoding

            enc = negotiate_encoding(
                req_headers.get("Accept-Encoding", ""))
            if enc:
                payload = distributor.encoded(cache_key, blob, enc)
                headers["Content-Encoding"] = enc
                incr_counter("distrib", "bytes_sent",
                             value=float(len(payload)))
                return 200, payload, headers
        incr_counter("distrib", "bytes_sent", value=float(len(blob)))
        return 200, blob, headers

    def handle_filter(self, rest: str, req_headers=None):
        """The distribution surface (docs/FILTER_FORMAT.md formats):

        - ``GET /filter`` — the latest full ``CTMRFL01`` artifact;
        - ``GET /filter/manifest`` — the chain manifest JSON (latest
          epoch + hash, delta links with per-link SHA-256, anchors);
        - ``GET /filter/container/<kind>`` — the latest artifact in an
          upstream container encoding (``mlbf`` | ``clubcard``);
        - ``GET /filter/delta/<from>/<to>`` — the concatenated
          ``CTMRDL01`` links replaying epoch *from* to *to* (404 ⇒
          no contiguous chain: full-pull);
        - ``GET /filter/<issuer>/<expDate>`` — a standalone
          single-group artifact slice.

        Every binary answer carries a strong ETag (SHA-256 of the
        deterministic bytes — identical on every worker of a fleet),
        honors ``If-None-Match`` with 304, negotiates
        gzip/zstd via ``Accept-Encoding``, and sets per-artifact
        ``Cache-Control``/``Last-Modified``. 404 when the tier is cold
        or the resource is unknown."""
        tier = self.oracle.filter_tier
        distributor = self.oracle.distributor
        latest = distributor.latest() if distributor is not None else None
        if tier is None and latest is None:
            return 404, {"error": "filter tier not armed "
                                  "(emitFilter / refresh_filter)"}
        parts = [p for p in rest.split("/") if p] if rest else []
        if not parts:
            incr_counter("distrib", "http_full")
            if latest is not None:
                return self._blob_response(
                    latest.blob, latest.etag, req_headers,
                    self._CC_LATEST, cache_key=("full", latest.epoch),
                    created_wall=latest.created_wall,
                    epoch=latest.epoch)
            blob = tier.artifact.to_bytes()
            from ct_mapreduce_tpu.distrib import publish as _pub

            return self._blob_response(blob, _pub.etag_of(blob),
                                       req_headers, self._CC_LATEST,
                                       epoch=tier.epoch)
        if parts[0] == "manifest":
            if distributor is None:
                return 404, {"error": "distribution store not armed"}
            incr_counter("distrib", "http_manifest")
            return 200, distributor.manifest()
        if parts[0] == "container":
            if latest is None:
                return 404, {"error": "no published artifact"}
            if len(parts) != 2 or parts[1] not in latest.containers:
                return 404, {"error": "unknown container kind",
                             "kinds": sorted(latest.containers)}
            incr_counter("distrib", "http_container")
            return self._blob_response(
                latest.containers[parts[1]],
                latest.container_etags[parts[1]], req_headers,
                self._CC_LATEST,
                cache_key=("container", latest.epoch, parts[1]),
                created_wall=latest.created_wall, epoch=latest.epoch)
        if parts[0] == "delta":
            if distributor is None:
                return 404, {"error": "distribution store not armed"}
            if len(parts) != 3:
                return 400, {"error": "use /filter/delta/<from>/<to>"}
            try:
                from_e, to_e = int(parts[1]), int(parts[2])
            except ValueError:
                return 400, {"error": "delta epochs must be integers"}
            bundle = distributor.delta_bundle(from_e, to_e)
            if bundle is None:
                return 404, {"error": "no delta chain",
                             "fromEpoch": from_e, "toEpoch": to_e,
                             "hint": "full-pull /filter"}
            incr_counter("distrib", "http_delta")
            from ct_mapreduce_tpu.distrib import publish as _pub

            return self._blob_response(
                bundle, _pub.etag_of(bundle), req_headers,
                self._CC_IMMUTABLE, cache_key=("delta", from_e, to_e),
                epoch=to_e)
        if len(parts) != 2:
            return 400, {"error": "use /filter/<issuer>/<expDate>"}
        art = (tier.artifact if tier is not None
               else None)
        if art is None:
            from ct_mapreduce_tpu.filter import FilterArtifact

            art = FilterArtifact.from_bytes(latest.blob)
        blob = art.group_bytes(parts[0], parts[1])
        if blob is None:
            return 404, {"error": "no filter group",
                         "issuer": parts[0], "expDate": parts[1]}
        from ct_mapreduce_tpu.distrib import publish as _pub

        return self._blob_response(blob, _pub.etag_of(blob),
                                   req_headers, self._CC_LATEST)

    def handle_healthz(self) -> tuple[int, dict]:
        from ct_mapreduce_tpu.telemetry.metrics import get_sink

        counters = get_sink().snapshot().get("counters", {})
        # SLO hook (round 23): ct-fetch attaches its rule evaluation;
        # any breach renders the same JSON body under HTTP 503 so load
        # balancers act on the code while operators read the reasons.
        degraded: list = []
        if self.slo_check is not None:
            try:
                degraded = list(self.slo_check())
            except Exception as err:  # the probe must answer, not 500
                degraded = [f"slo check failed: "
                            f"{type(err).__name__}: {err}"]
        body = {
            "healthy": not degraded,
            **self.oracle.stats(),
            "shed_total": counters.get("serve.shed", 0.0),
            "batches_total": counters.get("serve.batches", 0.0),
            "cache_hit_total": counters.get("serve.cache_hit", 0.0),
            "cache_miss_total": counters.get("serve.cache_miss", 0.0),
            "device_fallback_total": counters.get(
                "serve.device_fallback", 0.0),
        }
        if degraded:
            body["degraded"] = degraded
        return (503 if degraded else 200), body

    def handle_getcert(self, params: dict) -> tuple[int, dict]:
        log_url = params.get("log")
        index = params.get("index")
        if not log_url or index is None:
            return 400, {"error": "log and index are required"}
        try:
            index = int(index)
        except ValueError:
            return 400, {"error": f"index is not an integer: {index!r}"}
        from ct_mapreduce_tpu.core.der import der_to_pem
        from ct_mapreduce_tpu.ingest.ctclient import CTLogClient
        from ct_mapreduce_tpu.ingest.leaf import (
            LeafDecodeError,
            decode_json_entry,
        )

        try:
            client = CTLogClient(log_url, transport=self._transport)
            entries = client.get_raw_entries(index, index)
        except Exception as err:
            return 502, {"error": f"log fetch failed: {err}"}
        pems = []
        for raw in entries:
            try:
                entry = decode_json_entry(
                    raw.index,
                    {"leaf_input": raw.leaf_input,
                     "extra_data": raw.extra_data},
                )
            except LeafDecodeError as err:
                return 502, {"error": f"undecodable entry: {err}"}
            pem = der_to_pem(entry.cert_der)
            pems.append(pem.decode() if isinstance(pem, bytes) else pem)
        if not pems:
            return 404, {"error": f"no entry at index {index}"}
        return 200, {"log": log_url, "index": index, "pem": "".join(pems)}

    # -- server lifecycle ------------------------------------------------
    def start(self) -> "QueryServer":
        server = self

        class Handler(BaseHTTPRequestHandler):
            def _trace_ctx(self):
                """Cross-process correlation (round 23): adopt the
                client's traceparent header so every span this request
                produces on this thread carries its trace_id."""
                ids = trace.parse_traceparent(
                    self.headers.get(trace.TRACEPARENT_HEADER, "") or "")
                if ids is None:
                    return trace.trace_context(None)
                return trace.trace_context(*ids)

            def _respond(self, code: int, body, headers=None) -> None:
                if isinstance(body, (bytes, bytearray)):
                    payload, ctype = bytes(body), "application/octet-stream"
                else:
                    payload, ctype = json.dumps(body).encode(), \
                        "application/json"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                for name, value in sorted((headers or {}).items()):
                    self.send_header(name, value)
                self.end_headers()
                # Large-artifact publish path (round 19): a 10⁸-scale
                # filter is ~100 MB — stream it in 1 MB slices so the
                # socket layer never buffers a second full copy and
                # slow clients don't pin one giant write.
                view = memoryview(payload)
                for off in range(0, len(view), 1 << 20):
                    self.wfile.write(view[off: off + (1 << 20)])
                if code >= 400:
                    incr_counter("serve", "http_errors")

            def do_POST(self):  # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                if path != "/query":
                    self._respond(404, {"error": "not found"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    body = json.loads(self.rfile.read(length) or b"{}")
                    if not isinstance(body, dict):
                        raise ValueError("body must be a JSON object")
                except (ValueError, json.JSONDecodeError) as err:
                    self._respond(400, {"error": f"bad request: {err}"})
                    return
                try:
                    with self._trace_ctx():
                        self._respond(*server.handle_query(body))
                except Exception as err:  # the server must answer
                    self._respond(
                        500, {"error": f"{type(err).__name__}: {err}"})

            def do_GET(self):  # noqa: N802
                raw_path, _, qs = self.path.partition("?")
                path = raw_path.rstrip("/") or "/"
                with self._trace_ctx():
                    self._dispatch_get(path, qs)

            def _dispatch_get(self, path: str, qs: str) -> None:
                try:
                    if path == "/healthz":
                        self._respond(*server.handle_healthz())
                    elif path.startswith("/issuer/"):
                        from urllib.parse import unquote

                        self._respond(*server.handle_issuer(
                            unquote(path[len("/issuer/"):])))
                    elif path == "/filter" or path.startswith("/filter/"):
                        from urllib.parse import unquote

                        self._respond(*server.handle_filter(
                            unquote(path[len("/filter"):]).lstrip("/"),
                            req_headers=self.headers))
                    elif path == "/getcert":
                        from urllib.parse import parse_qsl

                        self._respond(
                            *server.handle_getcert(dict(parse_qsl(qs))))
                    else:
                        self._respond(404, {"error": "not found"})
                except Exception as err:
                    self._respond(
                        500, {"error": f"{type(err).__name__}: {err}"})

            def log_message(self, *args):  # no per-request stderr spam
                pass

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._server.server_address[1]  # resolve port 0
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="query-serve",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        self.oracle.close()
