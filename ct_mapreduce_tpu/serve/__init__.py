"""Query plane: online membership serving over the live dedup table.

The map side of the system (ingest → dedup → counts) answers "has this
certificate been seen?" only in batch — ``storage-statistics`` drains a
snapshot and prints text. This package is the read/serve side: a
batched membership oracle ("is serial S known for (issuer, expDate)?")
plus per-issuer metadata lookups, served at high QPS against the LIVE
aggregator state while ingest keeps running.

Three pieces (ISSUE 5):

- :mod:`~ct_mapreduce_tpu.serve.snapshot` — epoch-pinned, immutable
  read views captured under the aggregator's fold/table locks, so a
  mid-grow or mid-insert step never tears a read; staleness is bounded
  and surfaced per response.
- :mod:`~ct_mapreduce_tpu.serve.batcher` — deadline-driven dynamic
  micro-batching (the inference-serving discipline): concurrent
  requests coalesce into one padded pow2-width ``contains`` batch,
  with max-batch / max-delay knobs, per-request deadlines, and a
  bounded admission queue that sheds with explicit ``overloaded``
  rejections instead of queueing without bound.
- :mod:`~ct_mapreduce_tpu.serve.server` — the stdlib HTTP JSON API
  (``queryPort`` directive; ``/query``, ``/issuer/<id>``,
  ``/healthz``, ``/getcert``) and the
  :class:`~ct_mapreduce_tpu.serve.server.MembershipOracle` that ties
  the two together. :mod:`~ct_mapreduce_tpu.serve.client` is the
  matching client (the ``ct-query`` binary).
"""

from ct_mapreduce_tpu.serve.batcher import (  # noqa: F401
    DeadlineExceeded,
    MicroBatcher,
    Overloaded,
)
from ct_mapreduce_tpu.serve.cache import HotSerialCache  # noqa: F401
from ct_mapreduce_tpu.serve.snapshot import (  # noqa: F401
    ReplicaPool,
    SnapshotManager,
    TableView,
)
