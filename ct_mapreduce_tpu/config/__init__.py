from ct_mapreduce_tpu.config.config import CTConfig  # noqa: F401
