"""platformProfile: tuned device profiles as data files, one loader
for every subsystem's knobs (ROADMAP item 1's unlocking refactor).

Knob resolution used to be scattered across per-subsystem
``resolve_*`` functions, each hand-rolling the same precedence ladder.
They now all declare their knobs as :class:`Knob` specs and resolve
through :func:`resolve_section`, which inserts ONE new layer — the
platform profile — into the ladder:

    explicit (config directive / kwarg)
      > CTMR_* env var
        > platform profile (this module)
          > built-in default

A profile is a JSON file (the ``platformProfile`` directive or the
``CTMR_PLATFORM_PROFILE`` env var):

.. code-block:: json

    {"version": 1,
     "platform": "tpu-v5e-8",
     "knobs": {"staging": {"chunksPerDispatch": 8, "stagingDepth": 3},
               "serve":   {"serveReplicas": 4},
               "verify":  {"verifyPrecompWindow": 16},
               "fleet":   {"numWorkers": 4},
               "filter":  {"filterFpRate": 0.005},
               "distrib": {"maxDeltaChain": 8}}}

so the autotuner campaign (ROADMAP item 1) emits a versioned data
file and every subsystem picks its knobs up with zero code changes —
"a tuned device profile is a data file, not a PR". Knob names inside a
section are the directive spellings (``chunksPerDispatch``, not
``chunks_per_dispatch``). Unknown sections/knobs are ignored (forward
compatibility); an unreadable profile warns once and resolves as if
absent (the config layer's unparseable-value tolerance).

Round 21 (the autotuner, ``ct_mapreduce_tpu/tune/``) grows two
optional top-level blocks:

- ``"fingerprint"``: the platform identity the profile was measured
  on (:func:`current_fingerprint` — jax backend, device kind, device
  count, host cores). When present, it is compared against this
  host's fingerprint on the keys BOTH sides carry; a mismatch warns
  once and the profile resolves as if absent — a v5e-tuned profile
  must never silently steer a CPU box (or vice versa). Profiles
  without the block (round-18 hand-written ones) load as before.
- ``"provenance"``: per-knob measurement evidence (curves, reps,
  wall seconds) written by ``tune/emit.py``. The loader tolerates and
  ignores it — provenance is for humans and for ``ctmr-tune show``,
  never for resolution.

The config-parity lint rule covers this layer: every ``CTMR_*`` env
named in a :class:`Knob` spec must be documented in MIGRATING.md, and
every section name resolved here must appear in MIGRATING.md's
platformProfile documentation.
"""

from __future__ import annotations

import json
import os
import sys
from dataclasses import dataclass
from typing import Any, Callable, Optional

PROFILE_VERSION = 1

# Active profile state: explicit path (set_active_profile, from the
# platformProfile directive) beats the CTMR_PLATFORM_PROFILE env.
# Loaded profiles cache by path; a failed load caches the failure so
# the warning prints once per path, not per knob resolution.
_active_path: Optional[str] = None
_cache: dict[str, Optional[dict]] = {}


def set_active_profile(path: Optional[str]) -> None:
    """Pin the active profile path (ct-fetch calls this with the
    ``platformProfile`` directive before building any subsystem).
    ``None``/empty falls back to the CTMR_PLATFORM_PROFILE env."""
    global _active_path
    _active_path = path or None


def active_profile_path() -> str:
    return _active_path or os.environ.get("CTMR_PLATFORM_PROFILE", "")


def current_fingerprint() -> dict:
    """This host's platform identity, the key a tuned profile is
    matched against: jax backend + first-device kind + device count +
    host cores. jax imports lazily (and only when a profile actually
    carries a fingerprint block) so profile resolution never forces
    device acquisition; with jax unavailable the fingerprint degrades
    to the host-only keys and matching proceeds on those."""
    fp: dict = {"host_cores": os.cpu_count() or 1}
    try:
        import jax

        devs = jax.devices()
        fp["jax_backend"] = jax.default_backend()
        fp["device_kind"] = devs[0].device_kind if devs else ""
        fp["device_count"] = len(devs)
    except Exception:  # pragma: no cover - jax import/init failure
        pass
    return fp


def fingerprint_matches(profile_fp: dict,
                        current_fp: Optional[dict] = None) -> bool:
    """True when the profile's fingerprint agrees with this host on
    every key BOTH sides carry (a partial fingerprint — e.g. a profile
    that only pins ``device_kind`` — matches any host with that
    device). An empty/absent fingerprint matches everything: round-18
    profiles predate the block."""
    if not isinstance(profile_fp, dict) or not profile_fp:
        return True
    cur = current_fingerprint() if current_fp is None else current_fp
    return all(profile_fp[k] == cur[k] for k in profile_fp
               if k in cur)


def load_profile(path: str) -> Optional[dict]:
    """Parse + validate one profile file; None (with a one-time
    warning) when unreadable — a bad profile must never kill a run,
    matching the config layer's tolerance for unparseable values.
    A profile carrying a ``fingerprint`` block that does not match
    this host is rejected the same way (warn once, resolve as if
    absent); a ``provenance`` block is validated for shape and then
    ignored by resolution."""
    cached = _cache.get(path, False)
    if cached is not False:
        return cached
    prof: Optional[dict] = None
    try:
        with open(path) as fh:
            data = json.load(fh)
        if not isinstance(data, dict) or not isinstance(
                data.get("knobs", {}), dict):
            raise ValueError("profile must be a JSON object with a "
                             "'knobs' object")
        if data.get("version", PROFILE_VERSION) != PROFILE_VERSION:
            raise ValueError(f"unsupported profile version "
                             f"{data.get('version')!r}")
        fp = data.get("fingerprint")
        if fp is not None and not isinstance(fp, dict):
            raise ValueError("'fingerprint' must be a JSON object")
        if fp and not fingerprint_matches(fp):
            raise ValueError(
                f"platform fingerprint mismatch: profile measured on "
                f"{fp!r}, this host is {current_fingerprint()!r}")
        prov = data.get("provenance")
        if prov is not None and not isinstance(prov, dict):
            raise ValueError("'provenance' must be a JSON object")
        prof = data
    except (OSError, ValueError) as err:
        print(f"platformProfile ignored ({path}): {err}",
              file=sys.stderr)
    _cache[path] = prof
    return prof


def invalidate_cache(path: Optional[str] = None) -> None:
    """Drop the load cache for one path (or all): the autotuner emits
    a profile and immediately resolves through it, and tests rewrite
    profile bytes at a reused path."""
    if path is None:
        _cache.clear()
    else:
        _cache.pop(path, None)


def profile_value(section: str, name: str) -> Any:
    """The active profile's value for one knob, or None."""
    path = active_profile_path()
    if not path:
        return None
    prof = load_profile(path)
    if not prof:
        return None
    knobs = prof.get("knobs", {})
    sec = knobs.get(section)
    if not isinstance(sec, dict):
        return None
    return sec.get(name)


# -- the knob engine ------------------------------------------------------


def _default_is_set(v: Any) -> bool:
    return v is not None


@dataclass(frozen=True)
class Knob:
    """One tunable: its directive-spelled name, env var, default, and
    the per-layer semantics that differ knob to knob (when is an
    explicit value "set"? how does the env string parse?)."""

    name: str
    env: str = ""
    default: Any = None
    # env string -> typed value; raising means "unparseable, ignored".
    parse: Callable[[str], Any] = int
    # Explicit/profile values count only when is_set says so (e.g. 0 =
    # unset for positive-int knobs, -1 = unset for sentinel ints).
    is_set: Callable[[Any], bool] = _default_is_set
    # Parsed env values get their own test when the env layer's unset
    # convention differs (None = same as is_set).
    env_is_set: Optional[Callable[[Any], bool]] = None
    # Final clamp/normalization applied to whichever layer won.
    post: Optional[Callable[[Any], Any]] = None


# Layer names, in precedence order — the vocabulary `ctmr-tune show`
# and explain_section() speak.
LAYERS = ("explicit", "env", "profile", "default")


def _resolve_knob(section: str, knob: Knob,
                  explicit: dict) -> tuple[Any, str]:
    """One knob through the four-layer ladder: (pre-post value,
    winning layer name)."""
    ev = explicit.get(knob.name)
    if ev is not None and knob.is_set(ev):
        return ev, "explicit"
    if knob.env:
        raw = os.environ.get(knob.env, "")
        if raw:
            try:
                parsed = knob.parse(raw)
            except (TypeError, ValueError):
                parsed = None
            test = knob.env_is_set or knob.is_set
            if parsed is not None and test(parsed):
                return parsed, "env"
    pv = profile_value(section, knob.name)
    if pv is not None and knob.is_set(pv):
        return pv, "profile"
    return knob.default, "default"


def resolve_section(section: str, knobs: tuple,
                    explicit: dict) -> dict:
    """Run the four-layer ladder for every knob of one section.
    ``explicit`` maps knob names to directive/kwarg values (typed, not
    strings)."""
    out = {}
    for knob in knobs:
        value, _ = _resolve_knob(section, knob, explicit)
        if knob.post is not None:
            value = knob.post(value)
        out[knob.name] = value
    return out


def explain_section(section: str, knobs: tuple,
                    explicit: Optional[dict] = None) -> dict:
    """The debuggability half of the ladder (`ctmr-tune show`): the
    SAME resolution as :func:`resolve_section`, but each knob maps to
    ``{"value": <post-processed>, "layer": <winning layer>}`` so an
    operator can see which of explicit/env/profile/default actually
    decided every knob."""
    out = {}
    for knob in knobs:
        value, layer = _resolve_knob(section, knob, explicit or {})
        if knob.post is not None:
            value = knob.post(value)
        out[knob.name] = {"value": value, "layer": layer}
    return out


# -- shared predicates/parsers (the recurring knob shapes) ---------------


def pos_int(v: Any) -> bool:
    """Positive-int knobs: 0 (and below) means "unset"."""
    try:
        return int(v) > 0
    except (TypeError, ValueError):
        return False


def nonneg_int(v: Any) -> bool:
    """Sentinel-int knobs: -1 means "unset", 0 is a real value."""
    try:
        return int(v) >= 0
    except (TypeError, ValueError):
        return False


def nonzero_int(v: Any) -> bool:
    """Knobs where negative values are meaningful (e.g. -1 disables a
    cache): only exactly 0 means "unset"."""
    try:
        return int(v) != 0
    except (TypeError, ValueError):
        return False


def nonempty_str(v: Any) -> bool:
    return isinstance(v, str) and bool(v)


def pos_float(v: Any) -> bool:
    try:
        return float(v) > 0
    except (TypeError, ValueError):
        return False


def parse_bool_lenient(raw: str) -> bool:
    """The serve-plane convention: anything but an explicit false
    spelling is true."""
    return raw.strip().lower() not in ("0", "f", "false")


def parse_bool_strict(raw: str) -> bool:
    """The emit-style convention: only explicit true spellings are
    true."""
    return raw.strip().lower() in ("1", "t", "true")


def any_set(_v: Any) -> bool:
    """env_is_set for bool knobs: a present, parseable env var always
    decides (False included)."""
    return True
