"""Layered configuration: defaults < ini file < env vars < CLI flags.

Directive names, defaults, and precedence mirror the reference
(/root/reference/config/config.go:149-214): the ini section is
consulted first, an environment variable keyed by the directive name
overrides it, and a handful of CLI flags (-config, -offset, -limit,
-outputRefreshPeriod) override everything. The default config file is
~/.ct-fetch.ini when present (config.go:161-169).

TPU-specific directives are additive: `backend` selects the storage
execution path (noop | localdisk | redis | tpu — BASELINE.json's
`--backend=tpu` north star), `batchSize` / `meshShape` / `tableBits`
size the device pipeline.
"""

from __future__ import annotations

import argparse
import configparser
import os
from dataclasses import dataclass, fields
from pathlib import Path
from typing import Optional


@dataclass
class CTConfig:
    # Reference directives (config.go:184-202)
    offset: int = 0
    limit: int = 0
    log_url_list: str = ""  # "logList"
    num_threads: int = 1
    decode_workers: int = 0  # 0 = auto (cpu count); raw-batch decode pool
    decode_threads: int = 0  # 0 = auto; intra-chunk native decode threads
    # (the persistent C++ worker pool; CTMR_DECODE_THREADS equivalent)
    overlap_workers: int = 0  # >0 = overlapped ingest (decode‖device‖drain)
    preparsed_ingest: bool = False  # host sidecar extraction + walker-free
    # device step (CTMR_PREPARSED=1 equivalent; needs the native decoder)
    log_expired_entries: bool = False
    run_forever: bool = False
    polling_delay_mean: str = "10m"
    polling_delay_std_dev: int = 10
    save_period: str = "15m"
    issuer_cn_filter: str = ""
    cert_path: str = ""
    google_project_id: str = ""
    redis_host: str = ""
    redis_timeout: str = "5s"
    output_refresh_period: str = "125ms"
    stats_refresh_period: str = "10m"
    statsd_host: str = ""
    statsd_port: int = 0
    health_addr: str = ":8080"
    nobars: bool = False
    # TPU-native additions
    backend: str = ""  # "", noop, localdisk, redis, tpu
    batch_size: int = 65536
    table_bits: int = 22  # dedup table slots = 2**table_bits per shard
    table_grow_at: float = 0.7  # grow-and-rehash load factor; 0 disables
    table_max_bits: int = 28  # growth ceiling; past it, spill to host lane
    mesh_shape: str = ""  # e.g. "data:4,expert:2"; empty = all devices on data
    device_queue_depth: int = 2
    chunks_per_dispatch: int = 0  # K walker chunks per resident device
    # envelope (staged device queue); 0 = CTMR_CHUNKS_PER_DISPATCH env,
    # then 1 (legacy per-chunk dispatch)
    staging_depth: int = 0  # staged envelopes in flight before the
    # submit side blocks (H2D double-buffer depth); 0 =
    # CTMR_STAGING_DEPTH env, then 2
    agg_state_path: str = ""  # .npz snapshot of device aggregates (tpu backend)
    profile_dir: str = ""  # jax.profiler trace output dir (empty = off)
    trace_path: str = ""  # Chrome trace-event JSON of the ingest spans
    # (telemetry/trace.py; CTMR_TRACE env equivalent; empty = off)
    metrics_port: int = 0  # Prometheus /metrics + /healthz HTTP port
    # (telemetry/promhttp.py; 0 = off)
    query_port: int = 0  # batched membership-oracle JSON API port
    # (serve/server.py; 0 = off; tpu backend only)
    serve_replicas: int = 0  # epoch-pinned snapshot replicas in the
    # query plane's pool (0 = CTMR_SERVE_REPLICAS env, then 2)
    serve_device: bool = True  # serve membership from pinned device
    # copies (jitted contains); host-numpy fallback when no copy pins
    serve_cache_size: int = 0  # hot-serial result cache entries
    # (0 = CTMR_SERVE_CACHE_SIZE env, then 4096; -1 disables)
    verify_signatures: bool = False  # batched on-device SCT/ECDSA
    # verification lane (CTMR_VERIFY=1 equivalent; tpu backend only)
    verify_log_keys: str = ""  # JSON file of trusted log keys for the
    # verify lane (CTMR_VERIFY_KEYS equivalent; empty = no keys →
    # every SCT counts as verify.no_key)
    verify_precomp_window: int = -1  # windowed-precompute ladder width
    # in bits for the verify kernels (-1 = unset →
    # CTMR_VERIFY_PRECOMP_WINDOW env, then 8; 0 is a REAL value — the
    # legacy Jacobian ladder — so an explicit 0 beats a stray env)
    verify_qtable_size: int = 0  # per-curve device-resident per-log-
    # key Q-table LRU slots (0 = CTMR_VERIFY_QTABLE_SIZE env, then 32)
    num_workers: int = 0  # fleet size: logs partition across this many
    # ct-fetch workers by rendezvous hash (0 = CTMR_NUM_WORKERS env,
    # then 1 = single-worker)
    worker_id: int = -1  # this worker's id in [0, numWorkers)
    # (-1 = unset → CTMR_WORKER_ID env, then 0; 0 is a REAL id, so an
    # explicit workerId = 0 beats a stray env value)
    checkpoint_period: str = ""  # leader-published checkpoint cadence
    # (durable aggregate snapshot + cursors on every epoch tick;
    # "" = CTMR_CHECKPOINT_PERIOD env, then no fleet cadence — the
    # per-log savePeriod ticker still runs)
    coordinator_backend: str = ""  # fleet coordination fabric:
    # redis | jax | solo ("" = CTMR_COORDINATOR env, then redis when
    # numWorkers > 1, else solo)
    emit_filter: bool = False  # compile crlite-style filter artifacts
    # from the aggregation state at checkpoint time (CTMR_EMIT_FILTER
    # equivalent; tpu backend only)
    filter_path: str = ""  # filter artifact output path
    # ("" = CTMR_FILTER_PATH env, then <aggStatePath>.filter)
    filter_fp_rate: float = 0.0  # target layer-0 false-positive rate
    # (0 = CTMR_FILTER_FP_RATE env, then 0.01)
    filter_capture_spill_dir: str = ""  # spill-ring directory bounding
    # filter-capture RSS ("" = CTMR_FILTER_SPILL_DIR env, then
    # in-memory capture — round 19)
    filter_capture_spill_mb: int = 0  # capture memory tier in MB before
    # a spill flush (0 = CTMR_FILTER_SPILL_MB env, then 256)
    filter_stream_chunk: int = 0  # serials per streamed key block of
    # the filter build (0 = CTMR_FILTER_STREAM_CHUNK env, then 2^16)
    filter_fused_lanes: int = 0  # lanes per fused filter-build scatter
    # dispatch (0 = CTMR_FILTER_FUSED_LANES env, then 2^20)
    filter_format: str = ""  # artifact format, "fl01" | "fl02"
    # ("" = CTMR_FILTER_FORMAT env, then fl02 — round 20)
    platform_profile: str = ""  # tuned-knob profile JSON (one loader
    # for every subsystem's resolve_*; "" = CTMR_PLATFORM_PROFILE env)
    distrib_history: int = 0  # filter-distribution epochs held per
    # worker (0 = CTMR_DISTRIB_HISTORY env, then 8)
    max_delta_chain: int = 0  # delta links before a mandatory full-
    # snapshot anchor (0 = CTMR_MAX_DELTA_CHAIN env, then 4)
    checkpoint_mode: str = ""  # "ck01" full-only | "ck02" incremental
    # ("" = CTMR_CHECKPOINT_MODE env, then ck02 — round 22)
    ckpt_max_chain: int = 0  # CTMRCK02 delta segments before a
    # mandatory base anchor (0 = CTMR_CKPT_MAX_CHAIN env, then 8)
    ckpt_segment_budget_mb: int = 0  # dirty-log cap per tick; beyond
    # it the save anchors (0 = CTMR_CKPT_SEGMENT_BUDGET_MB, then 256)
    fleet_metrics: Optional[bool] = None  # publish this worker's
    # metrics snapshot through the coordinator fabric each heartbeat
    # and serve /metrics/fleet + /healthz/fleet (unset =
    # CTMR_FLEET_METRICS env, then on — round 23)
    slo_max_ingest_lag: int = 0  # SLO: max entries between the ingest
    # cursor and the STH tree head before /healthz degrades
    # (0 = CTMR_SLO_MAX_INGEST_LAG env, then disabled)
    slo_max_checkpoint_age: float = 0.0  # SLO: max seconds since the
    # last durable checkpoint, graded against max(this,
    # checkpointPeriod) (0 = CTMR_SLO_MAX_CKPT_AGE_S, then disabled)
    slo_max_filter_lag: int = 0  # SLO: max epochs the published filter
    # may trail the checkpoint epoch (0 = CTMR_SLO_MAX_FILTER_LAG env,
    # then disabled)
    slo_max_serve_p99_ms: float = 0.0  # SLO: max span-derived serve
    # p99 in ms (0 = CTMR_SLO_MAX_SERVE_P99_MS env, then disabled)
    audit_log_list: str = ""  # log-list v3 JSON path for the audit
    # subsystem ("" = CTMR_AUDIT_LOG_LIST env, then unset — round 24)
    audit_quarantine_dir: str = ""  # durable divergence spool ("" =
    # CTMR_AUDIT_QUARANTINE_DIR env, then in-memory only)
    verbosity: int = 0  # glog-style -v level (flag only, not a directive)

    _DIRECTIVES = {
        # directive name -> (field, type)
        "offset": ("offset", int),
        "limit": ("limit", int),
        "logList": ("log_url_list", str),
        "numThreads": ("num_threads", int),
        "decodeWorkers": ("decode_workers", int),
        "decodeThreads": ("decode_threads", int),
        "overlapWorkers": ("overlap_workers", int),
        "preparsedIngest": ("preparsed_ingest", bool),
        "logExpiredEntries": ("log_expired_entries", bool),
        "runForever": ("run_forever", bool),
        "pollingDelayMean": ("polling_delay_mean", str),
        "pollingDelayStdDev": ("polling_delay_std_dev", int),
        "savePeriod": ("save_period", str),
        "issuerCNFilter": ("issuer_cn_filter", str),
        "certPath": ("cert_path", str),
        "googleProjectId": ("google_project_id", str),
        "redisHost": ("redis_host", str),
        "redisTimeout": ("redis_timeout", str),
        "outputRefreshPeriod": ("output_refresh_period", str),
        "statsRefreshPeriod": ("stats_refresh_period", str),
        "statsdHost": ("statsd_host", str),
        "statsdPort": ("statsd_port", int),
        "healthAddr": ("health_addr", str),
        "backend": ("backend", str),
        "batchSize": ("batch_size", int),
        "tableBits": ("table_bits", int),
        "tableGrowAt": ("table_grow_at", float),
        "tableMaxBits": ("table_max_bits", int),
        "meshShape": ("mesh_shape", str),
        "deviceQueueDepth": ("device_queue_depth", int),
        "chunksPerDispatch": ("chunks_per_dispatch", int),
        "stagingDepth": ("staging_depth", int),
        "aggStatePath": ("agg_state_path", str),
        "profileDir": ("profile_dir", str),
        "tracePath": ("trace_path", str),
        "metricsPort": ("metrics_port", int),
        "queryPort": ("query_port", int),
        "serveReplicas": ("serve_replicas", int),
        "serveDevice": ("serve_device", bool),
        "serveCacheSize": ("serve_cache_size", int),
        "verifySignatures": ("verify_signatures", bool),
        "verifyLogKeys": ("verify_log_keys", str),
        "verifyPrecompWindow": ("verify_precomp_window", int),
        "verifyQTableSize": ("verify_qtable_size", int),
        "numWorkers": ("num_workers", int),
        "workerId": ("worker_id", int),
        "checkpointPeriod": ("checkpoint_period", str),
        "coordinatorBackend": ("coordinator_backend", str),
        "emitFilter": ("emit_filter", bool),
        "filterPath": ("filter_path", str),
        "filterFpRate": ("filter_fp_rate", float),
        "filterCaptureSpillDir": ("filter_capture_spill_dir", str),
        "filterCaptureSpillMB": ("filter_capture_spill_mb", int),
        "filterStreamChunk": ("filter_stream_chunk", int),
        "filterFusedLanes": ("filter_fused_lanes", int),
        "filterFormat": ("filter_format", str),
        "platformProfile": ("platform_profile", str),
        "distribHistory": ("distrib_history", int),
        "maxDeltaChain": ("max_delta_chain", int),
        "checkpointMode": ("checkpoint_mode", str),
        "ckptMaxChain": ("ckpt_max_chain", int),
        "ckptSegmentBudgetMB": ("ckpt_segment_budget_mb", int),
        "fleetMetrics": ("fleet_metrics", bool),
        "sloMaxIngestLag": ("slo_max_ingest_lag", int),
        "sloMaxCheckpointAge": ("slo_max_checkpoint_age", float),
        "sloMaxFilterLag": ("slo_max_filter_lag", int),
        "sloMaxServeP99Ms": ("slo_max_serve_p99_ms", float),
        "auditLogList": ("audit_log_list", str),
        "auditQuarantineDir": ("audit_quarantine_dir", str),
    }

    @classmethod
    def load(
        cls,
        argv: Optional[list[str]] = None,
        env: Optional[dict[str, str]] = None,
        default_ini: Optional[str] = None,
    ) -> "CTConfig":
        """Build a config from CLI argv (default: sys.argv[1:]) with the
        reference's layering."""
        env = os.environ if env is None else env
        parser = cls.arg_parser()
        args, _ = parser.parse_known_args(argv)

        cfg = cls()

        ini_path = args.config
        if not ini_path:
            if default_ini is not None:
                candidate = default_ini
            else:
                candidate = str(Path.home() / ".ct-fetch.ini")
            if os.path.exists(candidate):
                ini_path = candidate

        section = None
        if ini_path and os.path.exists(ini_path):
            parsed = configparser.ConfigParser()
            # Reference ini files use a top-level (unnamed) section; feed
            # configparser a synthetic [DEFAULT] header.
            with open(ini_path) as fh:
                content = fh.read()
            if not content.lstrip().startswith("["):
                content = "[DEFAULT]\n" + content
            parsed.read_string(content)
            section = parsed["DEFAULT"] if "DEFAULT" in parsed else None
            if section is None and parsed.sections():
                section = parsed[parsed.sections()[0]]

        def apply(field_name: str, typ, value: str) -> bool:
            try:
                if typ is bool:
                    v = value.strip().lower()
                    if v in ("1", "t", "true"):
                        parsed = True
                    elif v in ("0", "f", "false"):
                        parsed = False
                    else:  # Go strconv.ParseBool errors on anything else
                        return False
                else:
                    parsed = typ(value)
            except (TypeError, ValueError):
                return False  # unparseable values are ignored (config.go:41-60)
            setattr(cfg, field_name, parsed)
            return True

        for directive, (field_name, typ) in cls._DIRECTIVES.items():
            # Env beats file, but only when it parses — an unparseable
            # env var falls back to the file value (config.go:41-123).
            if directive in env and apply(field_name, typ, env[directive]):
                continue
            if section is not None and directive in section:
                apply(field_name, typ, section[directive])

        # CLI flags override everything (config.go:204-213)
        if args.offset:
            cfg.offset = args.offset
        if args.limit:
            cfg.limit = args.limit
        if args.outputRefreshPeriod != "125ms":
            cfg.output_refresh_period = args.outputRefreshPeriod
        if args.nobars:
            cfg.nobars = True
        if getattr(args, "backend", None):
            cfg.backend = args.backend
        cfg.verbosity = args.v
        return cfg

    @staticmethod
    def arg_parser() -> argparse.ArgumentParser:
        p = argparse.ArgumentParser(add_help=False)
        p.add_argument("-config", "--config", default="", help="configuration .ini file")
        p.add_argument("-offset", "--offset", type=int, default=0, help="offset from the beginning")
        p.add_argument("-limit", "--limit", type=int, default=0, help="limit processing to this many entries")
        p.add_argument(
            "-outputRefreshPeriod",
            "--outputRefreshPeriod",
            default="125ms",
            help="Speed for refreshing progress",
        )
        p.add_argument("-nobars", "--nobars", action="store_true", help="disable display of download bars")
        p.add_argument(
            "-backend",
            "--backend",
            default="",
            help="storage execution path: noop | localdisk | redis | tpu",
        )
        p.add_argument(
            "-v", "--v",
            # glog-style "-v=2" arrives from argparse as "=2" — accept it.
            type=lambda s: int(s.lstrip("=")),
            default=0,
            help="verbosity level (glog-style)",
        )
        return p

    def usage(self) -> str:
        """Self-documenting directive listing (config.go:216-244)."""
        lines = [
            "Environment variable or config file directives:",
            "",
            "Choose at most one backing store:",
            "certPath = Path under which to store full DER-encoded certificates",
            "",
            "The external data cache:",
            "redisHost = address:port of the Redis instance",
            "",
            "Options:",
            "issuerCNFilter = Prefixes to match for CNs for permitted issuers, comma delimited",
            "runForever = Run forever, pausing `pollingDelay` between runs",
            "pollingDelayMean = Wait a mean of this long between polls",
            "pollingDelayStdDev = Use this standard deviation between polls",
            "logExpiredEntries = Add expired entries to the database",
            "numThreads = Use this many threads for normal operations",
            "decodeWorkers = native leaf-decode threads (0 = cpu count)",
            "decodeThreads = intra-chunk native decode/sidecar threads "
            "(0 = CTMR_DECODE_THREADS, then cpu count; workers x threads "
            "should stay <= host cores)",
            "overlapWorkers = overlapped-ingest decode pool size (0 = serial dispatch)",
            "preparsedIngest = host sidecar extraction + walker-free device step",
            "savePeriod = Duration between state saves, e.g. 15m",
            "logList = URLs of the CT Logs, comma delimited",
            "outputRefreshPeriod = Period between output publications",
            "statsRefreshPeriod = Period between stats dumps to stderr",
            "statsdHost = host for StatsD information",
            "statsdPort = port for StatsD information",
            "redisTimeout = Timeout for operations from Redis, e.g. 10s",
            "healthAddr = Address for the /health http endpoint",
            "",
            "TPU execution:",
            "backend = noop | localdisk | redis | tpu",
            "batchSize = device batch size (entries per dispatch)",
            "tableBits = log2 of dedup-table slots per shard",
            "tableGrowAt = load factor that triggers grow-and-rehash (0 disables)",
            "tableMaxBits = log2 growth ceiling; beyond it lanes spill to the exact host lane",
            "meshShape = device mesh, e.g. data:4,expert:2",
            "deviceQueueDepth = host->device prefetch depth",
            "chunksPerDispatch = walker chunks fused into one resident "
            "device envelope (staged device queue; 1 = per-chunk "
            "dispatch, CTMR_CHUNKS_PER_DISPATCH equivalent)",
            "stagingDepth = staged envelopes in flight before the "
            "submit side blocks (H2D double-buffer depth, "
            "CTMR_STAGING_DEPTH equivalent)",
            "aggStatePath = Path for the on-device aggregate snapshot (.npz)",
            "profileDir = Write a jax.profiler trace of the run here",
            "tracePath = Write a Chrome trace-event JSON of the ingest "
            "spans here (CTMR_TRACE env equivalent)",
            "metricsPort = Serve Prometheus /metrics and /healthz on "
            "this port (0 disables)",
            "queryPort = Serve the batched membership-oracle JSON API "
            "(/query, /issuer, /getcert) on this port (0 disables)",
            "serveReplicas = epoch-pinned snapshot replicas in the "
            "query plane's pool (0 = CTMR_SERVE_REPLICAS, then 2; "
            "staggered refresh, round-robin serving)",
            "serveDevice = serve membership from pinned device copies "
            "via the jitted contains kernels (host-numpy fallback when "
            "no copy can pin; false forces the host mirror)",
            "serveCacheSize = hot-serial result cache entries in front "
            "of the batcher (0 = CTMR_SERVE_CACHE_SIZE, then 4096; "
            "-1 disables)",
            "verifySignatures = batched on-device SCT/ECDSA-P256 "
            "verification lane with pure-python host fallback "
            "(CTMR_VERIFY equivalent; per-issuer verified/failed "
            "counts in reports and /issuer)",
            "verifyLogKeys = JSON file of trusted CT log keys for the "
            "verify lane (CTMR_VERIFY_KEYS equivalent)",
            "verifyPrecompWindow = window width in bits for the "
            "verify kernels' precomputed-table ladders "
            "(CTMR_VERIFY_PRECOMP_WINDOW equivalent; default 8; an "
            "explicit 0 pins the legacy per-bit Jacobian ladder even "
            "when the env var is set)",
            "verifyQTableSize = per-curve device-resident per-log-key "
            "Q-table LRU slots for the windowed verify kernels "
            "(CTMR_VERIFY_QTABLE_SIZE equivalent; default 32 — size "
            "it at or above the live log-key count so steady state "
            "is 100% verify.qtable_hits)",
            "numWorkers = ingest fleet size: CT logs partition across "
            "this many workers by rendezvous hash; a single-log fleet "
            "stripes the entry-index space (CTMR_NUM_WORKERS "
            "equivalent)",
            "workerId = this worker's id in [0, numWorkers) "
            "(CTMR_WORKER_ID equivalent; an explicit 0 pins worker 0 "
            "even when the env var is set)",
            "checkpointPeriod = leader-published checkpoint cadence: "
            "every tick, each worker snapshots aggregates + cursors "
            "atomically for warm restart (CTMR_CHECKPOINT_PERIOD "
            "equivalent)",
            "coordinatorBackend = fleet coordination fabric: redis | "
            "jax | solo (CTMR_COORDINATOR equivalent; default redis "
            "when numWorkers > 1)",
            "emitFilter = compile a crlite-style filter-cascade "
            "artifact from the per-(issuer, expDate) known-serial "
            "sets on every checkpoint save (CTMR_EMIT_FILTER "
            "equivalent; a fleet leader also emits the merged fleet "
            "filter each epoch)",
            "filterPath = filter artifact output path "
            "(CTMR_FILTER_PATH equivalent; default "
            "<aggStatePath>.filter, per-worker suffixed in a fleet)",
            "filterFpRate = target layer-0 false-positive rate of the "
            "filter cascade (CTMR_FILTER_FP_RATE equivalent; default "
            "0.01; included serials are exact regardless)",
            "filterCaptureSpillDir = spill-ring directory for the "
            "filter capture: serial bytes overflow to durable segment "
            "files so capture RSS is bounded by filterCaptureSpillMB, "
            "not corpus size (CTMR_FILTER_SPILL_DIR equivalent; "
            "default in-memory capture; per-worker suffixed in a "
            "fleet; artifacts byte-identical either way)",
            "filterCaptureSpillMB = capture memory tier in MB before "
            "a spill flush (CTMR_FILTER_SPILL_MB equivalent; default "
            "256; only meaningful with filterCaptureSpillDir)",
            "filterStreamChunk = serials per streamed key block of "
            "the filter build (CTMR_FILTER_STREAM_CHUNK equivalent; "
            "default 2^16; bounds build transients, changes no bytes)",
            "filterFusedLanes = lanes per fused filter-build scatter "
            "dispatch (CTMR_FILTER_FUSED_LANES equivalent; default "
            "2^20; CTMR_FILTER_FUSED=0 forces the per-group build "
            "path — byte-identical)",
            "filterFormat = filter artifact format, fl01 | fl02 "
            "(CTMR_FILTER_FORMAT equivalent; default fl02 — per-group "
            "universes: decoupled deltas + dirty-group incremental "
            "rebuilds; fl01 is the global-universe compatibility path)",
            "platformProfile = tuned-knob profile JSON file "
            "(CTMR_PLATFORM_PROFILE equivalent): one loader feeds "
            "every subsystem's knob resolution, so a tuned device "
            "profile is a data file, not a code change — precedence "
            "explicit directive > CTMR_* env > profile > default",
            "distribHistory = filter-distribution epochs each worker "
            "holds for delta/conditional-GET serving "
            "(CTMR_DISTRIB_HISTORY equivalent; default 8)",
            "maxDeltaChain = delta links between mandatory full-"
            "snapshot anchors in the filter-distribution chain "
            "(CTMR_MAX_DELTA_CHAIN equivalent; default 4 — bounds a "
            "client's worst-case replay work)",
            "checkpointMode = aggregate-state checkpoint format: ck02 "
            "(default) appends O(churn) CTMRCK02 delta segments per "
            "epoch tick between full base anchors; ck01 writes the "
            "full .npz every tick (compatibility path and restore "
            "oracle) (CTMR_CHECKPOINT_MODE equivalent)",
            "ckptMaxChain = CTMRCK02 delta segments between mandatory "
            "base anchors (CTMR_CKPT_MAX_CHAIN equivalent; default 8 "
            "— bounds restore replay work)",
            "ckptSegmentBudgetMB = per-tick dirty-log budget; a tick "
            "whose churn exceeds it anchors with a full base instead "
            "(CTMR_CKPT_SEGMENT_BUDGET_MB equivalent; default 256)",
            "fleetMetrics = publish this worker's metrics snapshot "
            "through the coordinator fabric each heartbeat and serve "
            "the /metrics/fleet + /healthz/fleet fan-in "
            "(CTMR_FLEET_METRICS equivalent; default on — the payload "
            "rides a heartbeat already being sent)",
            "sloMaxIngestLag = degrade /healthz (HTTP 503) when any "
            "log's ingest cursor trails its STH tree head by more "
            "than this many entries (CTMR_SLO_MAX_INGEST_LAG "
            "equivalent; 0 = disabled)",
            "sloMaxCheckpointAge = degrade /healthz when the last "
            "durable checkpoint is older than this many seconds, "
            "graded against max(threshold, checkpointPeriod) so a "
            "threshold tighter than the cadence cannot flap "
            "(CTMR_SLO_MAX_CKPT_AGE_S equivalent; 0 = disabled)",
            "sloMaxFilterLag = degrade /healthz when the published "
            "filter epoch trails the checkpoint epoch by more than "
            "this many epochs (CTMR_SLO_MAX_FILTER_LAG equivalent; "
            "0 = disabled)",
            "sloMaxServeP99Ms = degrade /healthz when the span-"
            "derived serve p99 exceeds this many milliseconds "
            "(CTMR_SLO_MAX_SERVE_P99_MS equivalent; 0 = disabled)",
            "auditLogList = log-list v3 JSON (production Google/Apple "
            "schema) loaded as the audit subsystem's trust anchors "
            "(CTMR_AUDIT_LOG_LIST equivalent; unset = audit runs "
            "must name a list or use a recorded shard's embedded one)",
            "auditQuarantineDir = durable spool for native-vs-mirror "
            "divergence quarantine records (CTMR_AUDIT_QUARANTINE_DIR "
            "equivalent; unset = lanes are still excluded from "
            "aggregates, records stay in memory)",
            "",
            "Diagnostics (env only):",
            "CTMR_LOCK_WITNESS=1 wraps every lock the package creates "
            "in the runtime lock-order witness (analysis/witness.py): "
            "acquisition chains are checked live against the declared "
            "hierarchy (analysis/lockspec.py) and findings land in "
            "flight-recorder dumps. See docs/ANALYSIS.md; `ctmrlint` "
            "is the static half.",
        ]
        return "\n".join(lines)

    def log_urls(self) -> list[str]:
        return [u.strip() for u in self.log_url_list.split(",") if u.strip()]

    def issuer_cn_filters(self) -> list[str]:
        if not self.issuer_cn_filter:
            return []
        return self.issuer_cn_filter.split(",")
