"""RFC 6962 TLS-structure decoding for CT log entries.

The reference delegates this to certificate-transparency-go's
``ct.LogEntryFromLeaf`` (/root/reference/cmd/ct-fetch/ct-fetch.go:452)
and then stores either the X.509 leaf or the *submitted precertificate*
(``ep.Precert.Submitted``, ct-fetch.go:202-204) plus ``chain[0]`` as
the issuing certificate (ct-fetch.go:221). This module decodes the
same wire structures with a hand-rolled reader — there is no Python CT
library in the image, and the structures are small and stable:

  MerkleTreeLeaf   = version(1) ‖ leaf_type(1) ‖ TimestampedEntry
  TimestampedEntry = timestamp(8) ‖ entry_type(2) ‖ body ‖ extensions<2>
    x509_entry body    = ASN.1Cert<3>
    precert_entry body = issuer_key_hash(32) ‖ TBSCertificate<3>
  extra_data (x509)    = chain: ASN.1Cert<3> list inside a <3> frame
  extra_data (precert) = pre_certificate: ASN.1Cert<3> ‖ chain as above

``<N>`` denotes an N-byte big-endian length prefix (TLS opaque).

Decode failures raise :class:`LeafDecodeError`; callers treat them the
way the reference treats ``LogEntryFromLeaf`` errors — count, log,
skip, never fatal (ct-fetch.go:452-460).
"""

from __future__ import annotations

import base64
import struct
from dataclasses import dataclass, field
from typing import Optional

X509_ENTRY = 0
PRECERT_ENTRY = 1


class LeafDecodeError(ValueError):
    pass


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        if self.pos + n > len(self.buf):
            raise LeafDecodeError(
                f"truncated: need {n} bytes at offset {self.pos}, "
                f"have {len(self.buf) - self.pos}"
            )
        out = self.buf[self.pos : self.pos + n]
        self.pos += n
        return out

    def uint(self, width: int) -> int:
        return int.from_bytes(self.take(width), "big")

    def opaque(self, len_width: int) -> bytes:
        return self.take(self.uint(len_width))

    def remaining(self) -> int:
        return len(self.buf) - self.pos


@dataclass
class DecodedEntry:
    """One CT entry, decoded to what the store path needs.

    ``cert_der`` is the certificate the reference stores: the X.509
    leaf for x509 entries, the submitted precertificate (poison
    extension and all) for precert entries. ``issuer_der`` is
    ``chain[0]`` when the chain is non-empty.
    """

    index: int
    timestamp_ms: int
    entry_type: int
    cert_der: bytes
    issuer_der: Optional[bytes]
    chain: list[bytes] = field(default_factory=list)
    issuer_key_hash: Optional[bytes] = None  # precerts only

    @property
    def is_precert(self) -> bool:
        return self.entry_type == PRECERT_ENTRY


def _read_chain(r: _Reader) -> list[bytes]:
    """ASN.1CertChain: an outer <3> frame holding <3>-prefixed certs."""
    frame = _Reader(r.opaque(3))
    chain = []
    while frame.remaining():
        chain.append(frame.opaque(3))
    return chain


def decode_leaf_input(leaf_input: bytes) -> tuple[int, int, bytes, Optional[bytes]]:
    """→ (timestamp_ms, entry_type, body_der, issuer_key_hash).

    For x509 entries ``body_der`` is the full leaf certificate; for
    precert entries it is the TBSCertificate (which the reference does
    NOT store — it stores extra_data's submitted precert instead).
    """
    r = _Reader(leaf_input)
    version = r.uint(1)
    leaf_type = r.uint(1)
    if version != 0 or leaf_type != 0:
        raise LeafDecodeError(
            f"unsupported MerkleTreeLeaf version={version} type={leaf_type}"
        )
    timestamp_ms = r.uint(8)
    entry_type = r.uint(2)
    issuer_key_hash: Optional[bytes] = None
    if entry_type == X509_ENTRY:
        body = r.opaque(3)
    elif entry_type == PRECERT_ENTRY:
        issuer_key_hash = r.take(32)
        body = r.opaque(3)
    else:
        raise LeafDecodeError(f"unknown entry_type {entry_type}")
    r.opaque(2)  # CtExtensions — ignored, like the reference
    return timestamp_ms, entry_type, body, issuer_key_hash


def decode_entry(
    index: int, leaf_input: bytes, extra_data: bytes
) -> DecodedEntry:
    """Decode one get-entries element to the storable certificate."""
    timestamp_ms, entry_type, body, ikh = decode_leaf_input(leaf_input)
    r = _Reader(extra_data)
    if entry_type == X509_ENTRY:
        cert_der = body
        chain = _read_chain(r) if r.remaining() else []
    else:
        cert_der = r.opaque(3)  # the submitted precertificate
        chain = _read_chain(r) if r.remaining() else []
    return DecodedEntry(
        index=index,
        timestamp_ms=timestamp_ms,
        entry_type=entry_type,
        cert_der=cert_der,
        # A zero-length chain[0] counts as no issuer, like the native
        # decoder (ctmr_native.cpp CTMR_NO_CHAIN).
        issuer_der=chain[0] if chain and chain[0] else None,
        chain=chain,
        issuer_key_hash=ikh,
    )


def decode_json_entry(index: int, obj: dict) -> DecodedEntry:
    """Decode one element of a get-entries JSON response. Base64 is
    validated strictly — bad encodings raise :class:`LeafDecodeError`
    (same taxonomy as structural decode failures), keeping this path,
    the Python batch fallback, and the native decoder in agreement."""
    try:
        li = base64.b64decode(obj["leaf_input"], validate=True)
        ed = base64.b64decode(obj.get("extra_data", "") or "", validate=True)
    except (base64.binascii.Error, ValueError) as err:
        raise LeafDecodeError(f"bad base64: {err}") from None
    return decode_entry(index, li, ed)


def leaf_timestamp_ms(leaf_input_b64: str) -> Optional[int]:
    """Timestamp from a base64 leaf_input WITHOUT full decode — reads
    only the first 12 wire bytes (version ‖ type ‖ timestamp). Used by
    the raw-batch path to stamp checkpoints cheaply; returns None on
    any structural surprise."""
    try:
        head = base64.b64decode(leaf_input_b64[:16])
    except (ValueError, base64.binascii.Error):
        return None
    if len(head) < 10 or head[0] != 0 or head[1] != 0:
        return None
    return int.from_bytes(head[2:10], "big")


# ---------------------------------------------------------------------------
# Encoding — used by tests and the synthetic-log replay harness to build
# wire-faithful entries (the reference gets these from real logs).


def encode_leaf_input(
    cert_der: bytes,
    timestamp_ms: int = 0,
    entry_type: int = X509_ENTRY,
    issuer_key_hash: bytes = b"\x00" * 32,
) -> bytes:
    out = [b"\x00\x00", struct.pack(">QH", timestamp_ms, entry_type)]
    if entry_type == PRECERT_ENTRY:
        out.append(issuer_key_hash)
    out.append(len(cert_der).to_bytes(3, "big") + cert_der)
    out.append(b"\x00\x00")  # empty extensions
    return b"".join(out)


def encode_chain(chain: list[bytes]) -> bytes:
    inner = b"".join(len(c).to_bytes(3, "big") + c for c in chain)
    return len(inner).to_bytes(3, "big") + inner


def encode_extra_data(
    chain: list[bytes],
    entry_type: int = X509_ENTRY,
    pre_certificate: Optional[bytes] = None,
) -> bytes:
    if entry_type == PRECERT_ENTRY:
        if pre_certificate is None:
            raise ValueError("precert extra_data needs the submitted precert")
        return (
            len(pre_certificate).to_bytes(3, "big")
            + pre_certificate
            + encode_chain(chain)
        )
    return encode_chain(chain)
