"""Pod-scale multi-worker ingest: partitioned log feed and a
leader-coordinated worker lifecycle.

The reference scales out by running N independent ct-fetch processes
coordinated through Redis — SETNX leader election plus a polled start
barrier (/root/reference/coordinator/coordinator.go:44-138) — with the
log space split between them by operator config. This module makes
that a first-class mode of THIS binary, MapReduce-style (worker-
partitioned input, master-coordinated lifecycle, re-execution on
failure):

- **Partitioned feed** (`partition_map` / `partition_logs` /
  `partition_range`): a deterministic rendezvous hash over
  ``(worker_id, num_workers, log_url)`` assigns every configured CT
  log to exactly one worker, so no two workers fetch or double-count
  the same entries. A fleet pointed at ONE huge log instead splits its
  entry-index space into contiguous per-worker stripes
  (``partition_range``), each with its own durable cursor
  (``state_suffix`` in :class:`~ct_mapreduce_tpu.ingest.sync.LogWorker`).
  Partition maps are pure functions of the membership — every worker
  computes the same map with no communication — and are surfaced in
  ``/healthz`` via :meth:`FleetService.stats`.

- **Leader-coordinated lifecycle** (:class:`FleetCoordinator`
  implementations): one protocol over both fabrics — the Redis-parity
  :class:`~ct_mapreduce_tpu.coordinator.coordinator.Coordinator`
  (works against a real Redis or the in-tree miniredis) and the
  jax.distributed runtime (:class:`JaxFleetCoordinator`). Leader
  election, a start barrier, periodic per-worker heartbeats with a
  liveness timeout, and leader-published **epoch** ticks: the leader
  bumps a shared epoch counter every ``checkpointPeriod``, and every
  worker checkpoints when it observes the epoch advance — so the
  fleet's durable state moves in (approximate) lockstep instead of N
  free-running save tickers. A clean-shutdown broadcast rides the same
  value fabric.

- **Durable warm-restart**: checkpoints pair the aggregator's atomic
  ``.npz`` snapshot (write-to-temp + rename,
  :meth:`~ct_mapreduce_tpu.agg.aggregator.TpuAggregator.save_checkpoint`)
  with the per-log fetch cursors (``CertificateLog`` stamps, saved
  cursor-after-aggregate so the cursor never outruns durable aggregate
  state). A SIGKILLed worker resumes from its last checkpoint cursor —
  replaying only the post-checkpoint tail, which the dedup table folds
  idempotently — instead of re-fetching the log from entry zero.

Per-worker aggregates merge into one storage-statistics view through
:mod:`ct_mapreduce_tpu.agg.merge` (serial-set union + counter sum over
each worker's own drained snapshot).
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from datetime import datetime, timedelta, timezone
from typing import Callable, Iterable, Optional, Protocol, Sequence

from ct_mapreduce_tpu.config import profile as platprofile
from ct_mapreduce_tpu.telemetry import metrics, trace

# Cache key namespaces (alongside the reference's leader-/started-).
HEARTBEAT_KEY_PREFIX = "fleet-hb-"
EPOCH_KEY_PREFIX = "fleet-epoch-"
STOP_KEY_PREFIX = "fleet-stop-"
CLAIM_KEY_PREFIX = "fleet-claim-"
OBS_KEY_PREFIX = "fleet-obs-"

# A shutdown broadcast only needs to outlive every worker's observation
# poll (sub-second); the TTL bounds how long a stale broadcast can
# survive in a PERSISTENT Redis after the fleet is gone.
STOP_KEY_LIFE = timedelta(minutes=5)


# -- deterministic partitioner ------------------------------------------


def _weight(worker_id: int, num_workers: int, log_url: str) -> bytes:
    """Rendezvous (highest-random-weight) score of one (worker, log)
    pair. sha256 — NOT Python's randomized hash() — so every process
    in the fleet computes identical weights."""
    return hashlib.sha256(
        f"{worker_id}/{num_workers}/{log_url}".encode()
    ).digest()


def rendezvous_owner(log_url: str, num_workers: int,
                     candidates: Optional[Sequence[int]] = None) -> int:
    """The worker that owns ``log_url``: argmax of the rendezvous
    weight over ``candidates`` (default: all configured workers).
    Passing the alive subset reassigns only the dead owners' logs —
    the minimal-disruption property rendezvous hashing exists for."""
    ids = range(num_workers) if candidates is None else candidates
    return max(ids, key=lambda w: _weight(w, num_workers, log_url))


def partition_map(log_urls: Iterable[str], num_workers: int,
                  alive: Optional[Sequence[int]] = None) -> dict[str, int]:
    """log_url → owning worker id, deterministic for a given
    membership. With ``alive`` given, logs whose configured owner is
    dead re-home to the alive worker with the next-highest weight;
    logs with live owners never move."""
    out: dict[str, int] = {}
    for url in log_urls:
        owner = rendezvous_owner(url, num_workers)
        if alive is not None and owner not in alive and alive:
            owner = rendezvous_owner(url, num_workers, candidates=alive)
        out[url] = owner
    return out


def partition_logs(log_urls: Sequence[str], worker_id: int,
                   num_workers: int,
                   alive: Optional[Sequence[int]] = None) -> list[str]:
    """The subset of ``log_urls`` this worker fetches (order
    preserved)."""
    owners = partition_map(log_urls, num_workers, alive=alive)
    return [u for u in log_urls if owners[u] == worker_id]


def partition_range(tree_size: int, worker_id: int,
                    num_workers: int) -> tuple[int, int]:
    """(offset, limit) stripe of a single log's entry-index space for
    one worker: contiguous, disjoint, covering. Workers past the tree
    size get ``limit == 0`` (nothing to fetch)."""
    if num_workers <= 1:
        return 0, tree_size
    base, rem = divmod(max(tree_size, 0), num_workers)
    offset = worker_id * base + min(worker_id, rem)
    limit = base + (1 if worker_id < rem else 0)
    return offset, limit


def worker_state_path(path: str, worker_id: int, num_workers: int) -> str:
    """Per-worker aggregate-snapshot path: ``agg.npz`` →
    ``agg.w3.npz`` (suffix appended when there is no extension).
    Identity for single-worker runs, so existing configs keep their
    exact paths."""
    if not path or num_workers <= 1:
        return path
    root, ext = os.path.splitext(path)
    return f"{root}.w{worker_id}{ext}"


_FLEET_KNOBS = (
    platprofile.Knob("numWorkers", "CTMR_NUM_WORKERS", 1,
                     parse=int, is_set=platprofile.pos_int,
                     post=lambda v: max(1, int(v))),
    # -1 = unset; 0 is a REAL id (the one id every fleet must have
    # exactly once), so a config that pins workerId = 0 must beat a
    # stray env value.
    platprofile.Knob("workerId", "CTMR_WORKER_ID", 0,
                     parse=int, is_set=platprofile.nonneg_int,
                     post=lambda v: max(0, int(v))),
    platprofile.Knob("checkpointPeriod", "CTMR_CHECKPOINT_PERIOD", "",
                     parse=str, is_set=platprofile.nonempty_str),
    platprofile.Knob("coordinatorBackend", "CTMR_COORDINATOR", "",
                     parse=str, is_set=platprofile.nonempty_str),
)


def resolve_fleet(num_workers: int = 0, worker_id: int = -1,
                  checkpoint_period: str = "",
                  backend: str = "") -> tuple[int, int, str, str]:
    """Resolve the fleet knobs through the shared platformProfile
    ladder (config/profile.py): explicit value (config directive) >
    ``CTMR_NUM_WORKERS`` / ``CTMR_WORKER_ID`` /
    ``CTMR_CHECKPOINT_PERIOD`` / ``CTMR_COORDINATOR`` env > profile
    ``knobs.fleet`` > defaults (1 worker, id 0, no checkpoint cadence,
    auto backend). ``worker_id`` uses -1 as its unset sentinel.
    Unparseable env values are ignored, matching the config layer's
    tolerance."""
    r = platprofile.resolve_section("fleet", _FLEET_KNOBS, {
        "numWorkers": int(num_workers or 0),
        "workerId": int(worker_id),
        "checkpointPeriod": checkpoint_period,
        "coordinatorBackend": backend,
    })
    return (r["numWorkers"], r["workerId"], r["checkpointPeriod"],
            r["coordinatorBackend"])


# -- the coordinator protocol -------------------------------------------


class FleetCoordinator(Protocol):
    """One lifecycle contract over both coordination fabrics.

    ``start()`` contends for leadership (returns True iff won);
    ``barrier()`` releases every worker at once (leader publishes,
    followers wait); ``heartbeat()`` refreshes this worker's liveness
    lease; ``alive_workers()`` maps live worker ids to heartbeat ages;
    ``publish_epoch``/``current_epoch`` carry the leader's checkpoint
    cadence ticks; ``request_shutdown``/``shutdown_requested`` the
    clean-shutdown broadcast. ``fleet_started`` (after ``start()``)
    reports whether the current leadership already published its start
    barrier — i.e. this worker is REJOINING a running fleet;
    ``publish_start`` lets a rejoining leader re-publish the barrier
    without waiting for full membership. ``claim_log``/``release_log``
    are the per-log fetch lease: at most one worker holds a log at a
    time, so partition-map disagreement windows (dead-owner takeover
    racing the owner's warm restart) cannot double-fetch.
    ``publish_obs``/``fleet_obs`` carry each worker's TTL'd
    observability payload (metrics snapshot + clock pair, compact
    JSON) over the same value fabric — the metrics fan-in feed behind
    ``/metrics/fleet`` and ``/healthz/fleet``."""

    worker_id: int
    num_workers: int

    def start(self) -> bool: ...
    def barrier(self, timeout_s: Optional[float] = None) -> None: ...
    def fleet_started(self) -> bool: ...
    def publish_start(self) -> None: ...
    def heartbeat(self) -> None: ...
    def alive_workers(self) -> dict[int, float]: ...
    def maybe_promote(self) -> bool: ...
    def publish_epoch(self, epoch: int) -> None: ...
    def current_epoch(self) -> int: ...
    def request_shutdown(self, reason: str) -> None: ...
    def shutdown_requested(self) -> Optional[str]: ...
    def claim_log(self, log_url: str) -> bool: ...
    def release_log(self, log_url: str) -> None: ...
    def publish_obs(self, payload: str) -> None: ...
    def fleet_obs(self) -> dict[int, str]: ...
    def close(self) -> None: ...


class SoloFleetCoordinator:
    """The degenerate single-worker fleet: always leader, barrier and
    heartbeats are no-ops, epoch/shutdown are local state. Lets the
    checkpoint-cadence machinery run identically in one-process
    deployments (and in tests) without a cache."""

    def __init__(self, name: str = "ct-fetch"):
        self.name = name
        self.worker_id = 0
        self.num_workers = 1
        self.is_leader = True
        self._epoch = 0
        self._stop: Optional[str] = None
        self._beat = time.monotonic()

    def start(self) -> bool:
        return True

    def barrier(self, timeout_s: Optional[float] = None) -> None:
        pass

    def fleet_started(self) -> bool:
        return False

    def publish_start(self) -> None:
        pass

    def heartbeat(self) -> None:
        self._beat = time.monotonic()

    def alive_workers(self) -> dict[int, float]:
        return {0: time.monotonic() - self._beat}

    def maybe_promote(self) -> bool:
        return False

    def publish_epoch(self, epoch: int) -> None:
        self._epoch = int(epoch)

    def current_epoch(self) -> int:
        return self._epoch

    def request_shutdown(self, reason: str) -> None:
        self._stop = reason or "stop"

    def shutdown_requested(self) -> Optional[str]:
        return self._stop

    def claim_log(self, log_url: str) -> bool:
        return True  # sole worker: every log is uncontended

    def release_log(self, log_url: str) -> None:
        pass

    def publish_obs(self, payload: str) -> None:
        self._obs = payload

    def fleet_obs(self) -> dict[int, str]:
        obs = getattr(self, "_obs", None)
        return {0: obs} if obs is not None else {}

    def close(self) -> None:
        pass


class CacheFleetCoordinator:
    """The Redis-fabric coordinator: reference-parity SETNX election +
    start barrier (:class:`~ct_mapreduce_tpu.coordinator.coordinator.
    Coordinator`) extended with heartbeats, epoch publishing, and the
    shutdown broadcast over the same :class:`RemoteCache`.

    Heartbeats are TTL'd value writes (``fleet-hb-<name>-<id>`` →
    wall-clock stamp, expiring after ``liveness_timeout_s``): a worker
    is alive iff its key exists, and the stamp gives the age. The
    leader's election lease is the reference's own renewal-thread
    scheme; followers call :meth:`maybe_promote` when the leader's
    heartbeat disappears, and whoever wins the (now-expired) SETNX
    inherits leadership — elastic failover, exactly as the reference's
    lease expiry provides."""

    def __init__(self, cache, name: str, worker_id: int, num_workers: int,
                 liveness_timeout_s: float = 15.0,
                 poll_period_s: float = 0.05,
                 key_life_initial: timedelta = timedelta(minutes=5),
                 key_life_renewal: timedelta = timedelta(minutes=2)):
        from ct_mapreduce_tpu.coordinator.coordinator import Coordinator

        self.cache = cache
        self.name = name
        self.worker_id = int(worker_id)
        self.num_workers = int(num_workers)
        self.liveness_timeout_s = float(liveness_timeout_s)
        self.poll_period_s = float(poll_period_s)
        self.is_leader = False
        self._coord = Coordinator(
            cache, name,
            key_life_initial=key_life_initial,
            key_life_renewal=key_life_renewal,
            await_sleep_period_s=poll_period_s,
        )

    # -- keys ------------------------------------------------------------
    def _hb_key(self, worker_id: int) -> str:
        return f"{HEARTBEAT_KEY_PREFIX}{self.name}-{worker_id}"

    @property
    def _epoch_key(self) -> str:
        return EPOCH_KEY_PREFIX + self.name

    @property
    def _stop_key(self) -> str:
        return STOP_KEY_PREFIX + self.name

    def _claim_key(self, log_url: str) -> str:
        digest = hashlib.sha256(log_url.encode()).hexdigest()[:16]
        return f"{CLAIM_KEY_PREFIX}{self.name}-{digest}"

    def _obs_key(self, worker_id: int) -> str:
        return f"{OBS_KEY_PREFIX}{self.name}-{worker_id}"

    def _clear_key(self, key: str) -> None:
        """RemoteCache has no DEL; EXPIREAT in the past is the
        portable equivalent (Redis deletes the key immediately; the
        mock and miniredis purge it on the next touch)."""
        self.cache.expire_at(
            key, datetime(1970, 1, 2, tzinfo=timezone.utc))

    # -- lifecycle -------------------------------------------------------
    def start(self) -> bool:
        # Absorb a stale shutdown broadcast before anything can observe
        # it: against a PERSISTENT Redis, the previous run's signal-
        # driven stop key would otherwise self-terminate this run the
        # moment the service loop starts (the stop-key analog of
        # FleetService initializing _epoch_seen from current_epoch()).
        self._clear_key(self._stop_key)
        self.heartbeat()
        self.is_leader = self._coord.await_leader()
        return self.is_leader

    def barrier(self, timeout_s: Optional[float] = None) -> None:
        """Leader: wait until every configured worker has a live
        heartbeat, then publish the start key. Followers: poll for it
        (coordinator.go:87-138 semantics)."""
        if not self.is_leader:
            self._coord.await_start(timeout_s=timeout_s)
            return
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        while len(self.alive_workers()) < self.num_workers:
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(
                    f"start barrier: {sorted(self.alive_workers())} of "
                    f"{self.num_workers} workers present")
            time.sleep(self.poll_period_s)
        self._coord.send_start()

    def fleet_started(self) -> bool:
        """After ``start()``: has the CURRENT leadership already
        published its start barrier? True means this worker is
        rejoining a running fleet (its own barrier crossing happened in
        a previous incarnation) and must not block on — or re-form —
        the barrier. Scoped to the incumbent's election identifier, so
        a fresh fleet on a persistent Redis never false-positives on
        another run's leftovers (started keys are TTL'd and named by
        identifier)."""
        from ct_mapreduce_tpu.coordinator.coordinator import (
            STARTED_KEY_PREFIX,
        )

        ident = self._coord.identifier
        if not ident or self._coord.is_leader:
            # A leader's own started key can't predate its election:
            # identifiers are unique per await_leader() call.
            return False
        return self.cache.exists(STARTED_KEY_PREFIX + ident)

    def publish_start(self) -> None:
        """Leader-only: publish the start barrier WITHOUT waiting for
        full membership — the rejoin path (a restarted worker that
        inherited an expired lease must release any followers polling
        the barrier, and full membership may never re-form if peers
        already finished)."""
        self._coord.send_start()

    def heartbeat(self) -> None:
        self.cache.put(
            self._hb_key(self.worker_id), repr(time.time()),
            life=timedelta(seconds=self.liveness_timeout_s),
        )

    def alive_workers(self) -> dict[int, float]:
        now = time.time()
        out: dict[int, float] = {}
        for w in range(self.num_workers):
            raw = self.cache.get(self._hb_key(w))
            if raw is None:
                continue
            try:
                age = max(0.0, now - float(raw))
            except ValueError:
                age = 0.0
            out[w] = age
        return out

    def maybe_promote(self) -> bool:
        """Re-contend for leadership (no-op while someone else's lease
        is live — try_set loses). Returns True iff this worker just
        became leader."""
        if self.is_leader:
            return False
        self.is_leader = self._coord.await_leader()
        return self.is_leader

    # -- epoch / shutdown fabric ----------------------------------------
    def publish_epoch(self, epoch: int) -> None:
        self.cache.put(self._epoch_key, str(int(epoch)))

    def current_epoch(self) -> int:
        raw = self.cache.get(self._epoch_key)
        try:
            return int(raw) if raw else 0
        except ValueError:
            return 0

    def request_shutdown(self, reason: str) -> None:
        # TTL'd so a persistent Redis can't replay this broadcast into
        # a later run forever (start() also clears it defensively).
        self.cache.put(self._stop_key, reason or "stop",
                       life=STOP_KEY_LIFE)

    def shutdown_requested(self) -> Optional[str]:
        return self.cache.get(self._stop_key) or None

    # -- per-log fetch lease ---------------------------------------------
    def claim_log(self, log_url: str) -> bool:
        """Acquire (or re-affirm) the exclusive fetch lease on one log.
        SETNX with the worker id as the value: the holder re-affirms
        (refreshing the TTL — the renewal rides the FleetService
        heartbeat loop), everyone else is refused until the lease
        expires or is released. This is what makes dead-owner takeover
        safe against the owner's warm restart: both may COMPUTE
        ownership of the same log in the disagreement window, but only
        one can hold the lease, so entries are never fetched twice
        concurrently (agg/merge.py's disjointness assumption)."""
        me = str(self.worker_id)
        life = timedelta(seconds=self.liveness_timeout_s)
        holder = self.cache.try_set(self._claim_key(log_url), me, life)
        if holder != me:
            return False
        self.cache.put(self._claim_key(log_url), me, life=life)
        return True

    def release_log(self, log_url: str) -> None:
        if self.cache.get(self._claim_key(log_url)) == str(self.worker_id):
            self._clear_key(self._claim_key(log_url))

    # -- observability fan-in ---------------------------------------------
    def publish_obs(self, payload: str) -> None:
        """TTL'd like the heartbeat: a stalled worker's payload ages
        out of the fleet view on the same liveness clock that marks it
        dead, so the rollup never reports fresh-looking numbers from a
        SIGSTOP'd process."""
        self.cache.put(self._obs_key(self.worker_id), payload,
                       life=timedelta(seconds=self.liveness_timeout_s))

    def fleet_obs(self) -> dict[int, str]:
        out: dict[int, str] = {}
        for w in range(self.num_workers):
            raw = self.cache.get(self._obs_key(w))
            if raw is not None:
                out[w] = raw
        return out

    def close(self) -> None:
        self._coord.close()


class JaxFleetCoordinator:
    """The jax.distributed fabric: leadership is process_index 0, the
    barrier a device collective (parallel/distributed.py), liveness
    the runtime's own health checks, and the epoch/shutdown values
    ride the coordination service's key-value store. Single-process
    runs (no distributed client) degrade to local values so the
    cadence machinery still works.

    TPU-host validation pending, like ROADMAP items 1/3/4 — the CPU CI
    backend cannot run multiprocess collectives (see
    tests/test_multiprocess.py's capability gate)."""

    def __init__(self, name: str = "ct-fetch"):
        import jax

        from ct_mapreduce_tpu.parallel.distributed import (
            DistributedCoordinator,
        )

        self.name = name
        self.worker_id = jax.process_index()
        self.num_workers = jax.process_count()
        self.is_leader = False
        self._coord = DistributedCoordinator(name)
        self._local_epoch = 0
        self._local_stop: Optional[str] = None
        self._beat = time.monotonic()

    def start(self) -> bool:
        self.is_leader = self._coord.await_leader()
        return self.is_leader

    def barrier(self, timeout_s: Optional[float] = None) -> None:
        if self.num_workers <= 1:
            return
        if self.is_leader:
            self._coord.send_start()
        else:
            self._coord.await_start(timeout_s=timeout_s)

    def fleet_started(self) -> bool:
        # jax.distributed jobs form collectively: a dead process tears
        # the job down, so a single worker can never rejoin a running
        # fleet — every start is a cold start.
        return False

    def publish_start(self) -> None:
        if self.is_leader:
            self._coord.send_start()

    def heartbeat(self) -> None:
        self._beat = time.monotonic()

    def alive_workers(self) -> dict[int, float]:
        # The runtime evicts dead processes itself; every configured
        # worker that hasn't torn the job down is live by contract.
        return {w: 0.0 for w in range(self.num_workers)}

    def maybe_promote(self) -> bool:
        return False  # host-0 leadership is fixed by the runtime

    def _kv(self, key: str) -> str:
        return f"fleet/{self.name}/{key}"

    def publish_epoch(self, epoch: int) -> None:
        from ct_mapreduce_tpu.parallel import distributed

        if not distributed.kv_put(self._kv("epoch"), str(int(epoch))):
            self._local_epoch = int(epoch)

    def current_epoch(self) -> int:
        from ct_mapreduce_tpu.parallel import distributed

        raw = distributed.kv_get(self._kv("epoch"))
        if raw is None:
            return self._local_epoch
        try:
            return int(raw)
        except ValueError:
            return 0

    def request_shutdown(self, reason: str) -> None:
        from ct_mapreduce_tpu.parallel import distributed

        if not distributed.kv_put(self._kv("stop"), reason or "stop"):
            self._local_stop = reason or "stop"

    def shutdown_requested(self) -> Optional[str]:
        from ct_mapreduce_tpu.parallel import distributed

        raw = distributed.kv_get(self._kv("stop"))
        return raw if raw is not None else self._local_stop

    def claim_log(self, log_url: str) -> bool:
        # Membership is fixed by the runtime (alive_workers is always
        # the full set), so ownership never moves and leases are moot.
        return True

    def release_log(self, log_url: str) -> None:
        pass

    def publish_obs(self, payload: str) -> None:
        from ct_mapreduce_tpu.parallel import distributed

        if not distributed.kv_put(self._kv(f"obs/{self.worker_id}"),
                                  payload):
            self._local_obs = payload

    def fleet_obs(self) -> dict[int, str]:
        from ct_mapreduce_tpu.parallel import distributed

        out: dict[int, str] = {}
        for w in range(self.num_workers):
            raw = distributed.kv_get(self._kv(f"obs/{w}"))
            if raw is not None:
                out[w] = raw
        local = getattr(self, "_local_obs", None)
        if local is not None and self.worker_id not in out:
            out[self.worker_id] = local
        return out

    def close(self) -> None:
        self._coord.close()


def build_coordinator(backend: str, cache, name: str, worker_id: int,
                      num_workers: int, **kwargs) -> FleetCoordinator:
    """``coordinatorBackend`` directive → coordinator: ``redis`` (the
    configured RemoteCache — a real Redis via ``redisHost``, miniredis,
    or the in-process mock), ``jax`` (jax.distributed), ``solo``
    (single worker, no fabric). Empty picks ``redis`` for multi-worker
    configs and ``solo`` otherwise."""
    be = (backend or "").strip().lower()
    if not be:
        be = "redis" if num_workers > 1 else "solo"
    if be in ("solo", "none", "local"):
        return SoloFleetCoordinator(name)
    if be in ("redis", "cache"):
        if cache is None:
            raise ValueError("coordinatorBackend=redis needs a RemoteCache")
        if "liveness_timeout_s" not in kwargs:
            # CTMR_FLEET_LIVENESS_S shrinks the liveness TTL for test
            # harnesses that must observe a dead worker quickly (the
            # obs-smoke SIGSTOP leg); unparseable values are ignored,
            # matching the config layer's env tolerance.
            raw = os.environ.get("CTMR_FLEET_LIVENESS_S", "")
            try:
                if raw and float(raw) > 0:
                    kwargs["liveness_timeout_s"] = float(raw)
            except ValueError:
                pass
        return CacheFleetCoordinator(
            cache, name, worker_id, num_workers, **kwargs)
    if be == "jax":
        return JaxFleetCoordinator(name)
    raise ValueError(f"unknown coordinatorBackend {backend!r} "
                     "(expected redis | jax | solo)")


# -- the per-worker service ---------------------------------------------


class FleetService:
    """One worker's view of the fleet: election + barrier at start,
    then a background loop that heartbeats, watches the leader-
    published epoch (running ``on_checkpoint`` whenever it advances —
    the leader itself bumps it every ``checkpoint_period_s``), watches
    the shutdown broadcast (``on_shutdown``), and re-contends for
    leadership when the leader's heartbeat lapses. ``partition``
    filters a log list down to this worker's share and records the map
    for ``stats()`` / ``/healthz``."""

    def __init__(self, coordinator: FleetCoordinator,
                 heartbeat_period_s: float = 2.0,
                 checkpoint_period_s: float = 0.0,
                 on_checkpoint: Optional[Callable[[int], None]] = None,
                 on_shutdown: Optional[Callable[[str], None]] = None,
                 obs_payload: Optional[Callable[[], str]] = None):
        self.coordinator = coordinator
        self.worker_id = coordinator.worker_id
        self.num_workers = coordinator.num_workers
        self.heartbeat_period_s = max(0.05, float(heartbeat_period_s))
        self.checkpoint_period_s = max(0.0, float(checkpoint_period_s))
        self.on_checkpoint = on_checkpoint
        self.on_shutdown = on_shutdown
        # Zero-arg compact-JSON provider published into the fabric on
        # every heartbeat (telemetry/fleetobs.py builds it) — the
        # metrics fan-in + clock-pair exchange feed.
        self.obs_payload = obs_payload
        self.obs_publishes = 0
        self.is_leader = False
        self.rejoined = False
        self.checkpoints_run = 0
        self.last_checkpoint_wall = 0.0
        self._epoch_seen = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._shutdown_seen = False
        self._lock = threading.Lock()
        self._partition: dict[str, int] = {}
        self._stripe: Optional[dict] = None
        self._claims: set[str] = set()
        self._errors: list[str] = []

    # -- lifecycle -------------------------------------------------------
    def start(self, timeout_s: Optional[float] = None,
              await_barrier: bool = True, rejoin: bool = False) -> bool:
        """Elect, heartbeat, cross the start barrier, and start the
        background loop. A RESTARTED worker rejoining a running fleet
        must never block the resume on the original barrier (long
        published, and peers may already have finished): a rejoin is
        detected from the coordinator (the incumbent leadership's
        published start key) or asserted by the caller via ``rejoin``
        (e.g. a durable per-worker checkpoint on disk). A rejoining
        worker that inherited an expired leader lease re-publishes the
        start key instead of waiting for membership that may never
        re-form. ``await_barrier=False`` skips the barrier outright."""
        self.is_leader = self.coordinator.start()
        self.coordinator.heartbeat()
        self.rejoined = bool(rejoin) or self.coordinator.fleet_started()
        if await_barrier and not self.rejoined:
            self.coordinator.barrier(timeout_s=timeout_s)
        elif self.rejoined and self.is_leader:
            self.coordinator.publish_start()
        self._epoch_seen = self.coordinator.current_epoch()
        self._thread = threading.Thread(
            target=self._loop, name="fleet", daemon=True)
        self._thread.start()
        return self.is_leader

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.release_claims()
        self.coordinator.close()

    # -- background loop -------------------------------------------------
    def _loop(self) -> None:
        tick = min(self.heartbeat_period_s / 2.0, 0.25)
        next_beat = 0.0
        next_epoch_tick = (
            time.monotonic() + self.checkpoint_period_s
            if self.checkpoint_period_s else None)
        while not self._stop.wait(tick):
            try:
                now = time.monotonic()
                if now >= next_beat:
                    self.coordinator.heartbeat()
                    self._renew_claims()
                    self._publish_obs()
                    next_beat = now + self.heartbeat_period_s
                    self._observe_liveness()
                if (next_epoch_tick is not None and self.is_leader
                        and now >= next_epoch_tick):
                    self.coordinator.publish_epoch(
                        self.coordinator.current_epoch() + 1)
                    next_epoch_tick = now + self.checkpoint_period_s
                self._observe_epoch()
                self._observe_shutdown()
            except Exception as err:  # the loop must survive fabric blips
                with self._lock:
                    self._errors.append(f"{type(err).__name__}: {err}")
                    del self._errors[:-8]

    def _observe_liveness(self) -> None:
        alive = self.coordinator.alive_workers()
        metrics.set_gauge("fleet", "workers_alive", value=float(len(alive)))
        peer_ages = [a for w, a in alive.items() if w != self.worker_id]
        metrics.set_gauge("fleet", "heartbeat_age_s",
                          value=max(peer_ages, default=0.0))
        metrics.set_gauge("fleet", "is_leader",
                          value=1.0 if self.is_leader else 0.0)
        if not self.is_leader and self.coordinator.maybe_promote():
            self.is_leader = True

    def _publish_obs(self) -> None:
        if self.obs_payload is None:
            return
        try:
            payload = self.obs_payload()
        except Exception:
            return  # a snapshot failure must not stop the heartbeat
        if payload:
            self.coordinator.publish_obs(payload)
            self.obs_publishes += 1
            metrics.incr_counter("fleet", "obs_publishes")

    def fleet_obs(self) -> dict[int, str]:
        """Every live worker's published observability payload
        (worker id → compact JSON string, this worker included)."""
        return self.coordinator.fleet_obs()

    def _observe_epoch(self) -> None:
        epoch = self.coordinator.current_epoch()
        if epoch <= self._epoch_seen:
            return
        self._epoch_seen = epoch
        metrics.set_gauge("fleet", "checkpoint_epoch", value=float(epoch))
        # Cross-process correlation: every span this worker records
        # from here on carries the observed leader epoch.
        trace.set_process_attrs(epoch=epoch)
        if self.on_checkpoint is not None:
            with metrics.measure("fleet", "checkpoint_s"):
                self.on_checkpoint(epoch)
        self.checkpoints_run += 1
        self.last_checkpoint_wall = time.time()
        metrics.incr_counter("fleet", "checkpoint_count")

    def _observe_shutdown(self) -> None:
        if self._shutdown_seen:
            return
        reason = self.coordinator.shutdown_requested()
        if reason:
            self._shutdown_seen = True
            if self.on_shutdown is not None:
                self.on_shutdown(reason)

    # -- feed partitioning ----------------------------------------------
    def partition(self, log_urls: Sequence[str],
                  takeover: bool = False) -> list[str]:
        """This worker's share of the configured logs. With
        ``takeover`` (runForever rounds), logs whose configured owner
        has no live heartbeat re-home to live workers; one-shot runs
        stay on the configured map (the start barrier guaranteed full
        membership)."""
        alive = (sorted(self.coordinator.alive_workers())
                 if takeover else None)
        with self._lock:
            self._partition = partition_map(
                log_urls, self.num_workers, alive=alive)
            mine = [u for u in log_urls
                    if self._partition[u] == self.worker_id]
        metrics.set_gauge("fleet", "partition_size", value=float(len(mine)))
        return mine

    def stripe(self, tree_size: int) -> tuple[int, int]:
        """This worker's entry-index stripe of a single log."""
        return partition_range(tree_size, self.worker_id, self.num_workers)

    # -- per-log fetch leases --------------------------------------------
    def claim(self, log_url: str) -> bool:
        """Take the exclusive fetch lease on one partitioned log for
        this round; the background loop renews held leases every
        heartbeat. A refusal means another worker (takeover survivor
        or the restarted owner, whichever won) is mid-fetch — skip the
        log this round and re-contend on the next one."""
        ok = self.coordinator.claim_log(log_url)
        if ok:
            with self._lock:
                self._claims.add(log_url)
        metrics.set_gauge("fleet", "claims_held",
                          value=float(len(self._claims)))
        return ok

    def release_claims(self) -> None:
        """Drop every held lease (end of a sync round / shutdown) so
        the next round's rightful owners can take them."""
        with self._lock:
            claims, self._claims = sorted(self._claims), set()
        for url in claims:
            try:
                self.coordinator.release_log(url)
            except Exception:
                pass  # an unreleased lease just expires with its TTL
        metrics.set_gauge("fleet", "claims_held", value=0.0)

    def _renew_claims(self) -> None:
        with self._lock:
            claims = sorted(self._claims)
        for url in claims:
            self.coordinator.claim_log(url)

    def note_stripe(self, log_url: str, offset: int, limit: int) -> None:
        """Record a single-log entry-range assignment for stats() (the
        whole-log partition map doesn't apply in stripe mode)."""
        with self._lock:
            self._stripe = {"log_url": log_url, "offset": offset,
                            "limit": limit}
        metrics.set_gauge("fleet", "partition_size",
                          value=1.0 if limit > 0 else 0.0)

    def request_shutdown(self, reason: str) -> None:
        self.coordinator.request_shutdown(reason)

    def shutdown_requested(self) -> Optional[str]:
        return self.coordinator.shutdown_requested()

    # -- observability ---------------------------------------------------
    def stats(self) -> dict:
        """The ``/healthz`` fleet section: role, membership, heartbeat
        ages, the checkpoint epoch, and the current partition map."""
        alive = self.coordinator.alive_workers()
        with self._lock:
            partition = dict(self._partition)
            stripe = dict(self._stripe) if self._stripe else None
            claims = sorted(self._claims)
            errors = list(self._errors)
        body = {
            "role": "leader" if self.is_leader else "follower",
            "worker_id": self.worker_id,
            "num_workers": self.num_workers,
            "rejoined": self.rejoined,
            "claims": claims,
            "workers_alive": sorted(alive),
            "heartbeat_age_s": {str(w): round(a, 3)
                                for w, a in sorted(alive.items())},
            "checkpoint_epoch": self._epoch_seen,
            "checkpoints_run": self.checkpoints_run,
            "last_checkpoint_wall": self.last_checkpoint_wall,
            "obs_publishes": self.obs_publishes,
            "partition": partition,
        }
        if stripe is not None:
            body["stripe"] = stripe
        if errors:
            body["errors"] = errors
        return body
