"""Host-side ingest: CT log HTTP client, leaf decode, sync engine.

The reference's map side — download goroutines + parse/store worker
pool (/root/reference/cmd/ct-fetch/ct-fetch.go) — rebuilt as the host
pipeline that feeds packed entry batches to the device ops. Stage
layout mirrors §3.1-3.3 of SURVEY.md:

  ctclient    CT log v1 HTTP API (get-sth, get-entries×1000, 429 backoff)
  leaf        RFC 6962 TLS-struct decode (MerkleTreeLeaf, chains)
  sync        LogSyncEngine / LogWorker: download → queue → store workers
  fleet       multi-worker partitioned feed + leader-coordinated lifecycle
  health      /health endpoint (503 before first update, 500 stalled)
"""

from ct_mapreduce_tpu.ingest.ctclient import CTLogClient, SignedTreeHead, short_url
from ct_mapreduce_tpu.ingest.fleet import (
    FleetService,
    partition_logs,
    partition_map,
    partition_range,
)
from ct_mapreduce_tpu.ingest.leaf import DecodedEntry, decode_entry
from ct_mapreduce_tpu.ingest.overlap import OverlapError, OverlapIngestPipeline
from ct_mapreduce_tpu.ingest.sync import LogSyncEngine, LogWorker

__all__ = [
    "CTLogClient",
    "SignedTreeHead",
    "short_url",
    "DecodedEntry",
    "decode_entry",
    "FleetService",
    "LogSyncEngine",
    "LogWorker",
    "OverlapError",
    "OverlapIngestPipeline",
    "partition_logs",
    "partition_map",
    "partition_range",
]
