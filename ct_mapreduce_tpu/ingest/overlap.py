"""Overlapped ingest: decode ‖ H2D/device ‖ drain as a staged pipeline.

The round-5 e2e budget (BENCH_r05.json) was almost perfectly
serialized: decode 26.1 s, device wait 21.4 s, drain 4.9 s of a 57.6 s
wall for 2M entries — the device idle more than half the time while
the host decoded, the classic host-feed bottleneck that deep request
pipelining solves (cf. the FPGA ECDSA verification engine's request
queue, PAPERS.md). This module closes the gap structurally: while
batch N runs on device, batch N+1 decodes on a background pool through
the native leafpack path (``decode_raw_batch`` releases the GIL) and
its H2D transfer is submitted; batch N−1's drain (host-lane readback +
backend flush) is consumed from a bounded queue on a dedicated thread.
With decode and device fully overlapped, e2e wall drops toward
``max(decode, device)`` instead of their sum.

Stage layout (each box a thread or pool; queues are bounded):

    producer ──chunks──▶ [decode pool]      (sink._prepare_chunk)
                 │ futures, FIFO
                 ▼
             [submit thread]                (sink._submit_chunk, under
                 │ drain queue, ≤ depth      the dispatch lock; device
                 ▼                           steps dispatch async)
             [drain consumer]               (sink._complete_item:
                                             readback + PEM fold)

Ordering contract: chunks are SUBMITTED to the device in exactly the
order the producer handed them in (decode runs ahead out of order, a
reorder point at the submit thread restores it), and completions are
FIFO — so the dedup table sees the same insertion order as the serial
path and results are parity-identical (asserted by
tests/test_overlap.py and the bench smoke gate).

Failure contract: a stage exception (decode worker raise, submit
failure, drain failure) latches the pipeline into a failed state —
``submit_chunk``/``drain_all``/``close`` re-raise it as
:class:`OverlapError`, queues keep draining so nothing hangs, and
already-submitted device work is still completed (the aggregator's
counts stay exact for everything that reached the device).
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from ct_mapreduce_tpu.telemetry import flight, metrics, trace


class OverlapError(RuntimeError):
    """A pipeline stage failed; the original exception is ``__cause__``."""


_SENTINEL = object()


class OverlapIngestPipeline:
    """Three-stage overlap scheduler over one :class:`AggregatorSink`.

    ``decode_workers`` sizes the decode pool (each worker runs one
    whole-chunk native decode with the GIL released); ``queue_depth``
    bounds device batches that are submitted-but-undrained — the
    double-buffer depth. Memory bound: at most ``decode_workers + 1``
    prepared chunks plus ``queue_depth`` in-flight device batches are
    alive at once.

    **Sizing vs intra-chunk decode threads.** Host decode parallelism
    now has two axes: this pool runs W whole chunks concurrently, and
    inside each chunk the native worker pool splits lane ranges over T
    threads (``decodeThreads`` directive / ``CTMR_DECODE_THREADS``,
    ``leafpack.resolve_threads``). Both axes burn the same cores, so
    size them as **W × T ≤ host cores**: oversubscribing buys nothing
    (the native pool runs one parallel region at a time; an extra
    region decodes serially) and inflates the prepared-chunk memory
    window. ``decode_workers=0`` (the default) auto-sizes W from
    ``os.cpu_count() / T`` clamped to [1, 8] — with T at its own
    default (all cores) that is W=1, i.e. intra-chunk threads do the
    scaling and this pool only keeps one chunk decoding ahead of the
    device; pinning T smaller (e.g. ``decodeThreads=4`` on a 32-core
    host) shifts the parallelism back to whole-chunk pipelining.
    The ``overlapWorkers`` directive overrides W explicitly.
    """

    def __init__(self, sink, decode_workers: int = 0, queue_depth: int = 2,
                 max_prepared: Optional[int] = None):
        self._sink = sink
        if int(decode_workers) <= 0:
            decode_workers = self._auto_workers(sink)
        self.decode_workers = max(1, int(decode_workers))
        self.queue_depth = max(1, int(queue_depth))
        self._pool = ThreadPoolExecutor(
            max_workers=self.decode_workers, thread_name_prefix="ovl-decode"
        )
        # Reorder point: decode futures in producer order. The submit
        # loop waits on the HEAD future, so device submission order ==
        # producer order regardless of decode completion order.
        self._order_q: "queue.Queue" = queue.Queue()
        # Double buffer: blocks the submit loop once `queue_depth`
        # batches are submitted-but-undrained.
        self._drain_q: "queue.Queue" = queue.Queue(maxsize=self.queue_depth)
        self._failed = threading.Event()
        self._exc: Optional[BaseException] = None
        self._exc_lock = threading.Lock()
        # Bound decoded-but-unsubmitted chunks (each pins ~chunk bytes
        # twice: packed host rows + the enqueued device buffer).
        self._max_prepared = max_prepared or self.decode_workers + 1
        self._prepared_sem = threading.BoundedSemaphore(self._max_prepared)
        self._closed = False
        # Per-stage busy seconds (wall time spent inside the stage) —
        # the occupancy gauges bench.py reports. Busy sums exceeding
        # the wall clock is the overlap actually happening. "lock" is
        # the submit thread's wait for the sink's dispatch lock —
        # sampled SEPARATELY so the submit gauge (and the bench's
        # storeCertificate-derived dispatch budget) measures submit
        # work, not lock contention.
        self.busy = {"decode": 0.0, "submit": 0.0, "drain": 0.0,
                     "lock": 0.0}
        self._busy_lock = threading.Lock()
        # Bounded-queue depth high-water marks: how full the prepared
        # window (decoded-but-unsubmitted chunks) and the drain queue
        # (submitted-but-unfolded batches) ever got. A decode-starved
        # pipeline never fills the prepared window; a drain-starved one
        # pins the drain queue at its cap — the smoke gate reads these
        # gauges to tell the two apart.
        self.highwater = {"prepared": 0, "drain_queue": 0}
        self._prepared_in_use = 0
        self._hw_lock = threading.Lock()
        self._submit_t = threading.Thread(
            target=self._submit_loop, name="ovl-submit", daemon=True)
        self._drain_t = threading.Thread(
            target=self._drain_loop, name="ovl-drain", daemon=True)
        self._submit_t.start()
        self._drain_t.start()

    @staticmethod
    def _auto_workers(sink=None) -> int:
        """Default decode-pool width: the W of the W × T ≤ cores rule
        (docstring above), honoring the sink's configured intra-chunk
        thread count when it has one."""
        import os

        from ct_mapreduce_tpu.native import leafpack

        cores = os.cpu_count() or 1
        t = leafpack.resolve_threads(
            1 << 20, getattr(sink, "decode_threads", None))
        return max(1, min(8, cores // max(1, t)))

    # -- producer side ---------------------------------------------------
    def submit_chunk(self, pairs) -> None:
        """Enqueue one raw (leaf_input, extra_data) chunk for the
        pipeline. Blocks when the decode stage is saturated
        (backpressure toward the downloader queue); raises
        :class:`OverlapError` once any stage has failed."""
        if self._closed:
            raise OverlapError("overlap pipeline is closed")
        self._raise_if_failed()
        while not self._prepared_sem.acquire(timeout=0.1):
            # select{failure | slot} — a dead submit loop must surface
            # as an error here, never as a hung producer.
            self._raise_if_failed()
        with self._hw_lock:
            self._prepared_in_use += 1
            if self._prepared_in_use > self.highwater["prepared"]:
                self.highwater["prepared"] = self._prepared_in_use
        try:
            fut = self._pool.submit(self._decode_one, pairs)
        except BaseException:
            self._release_prepared()
            raise
        self._order_q.put(fut)

    def drain_all(self) -> None:
        """Barrier: block until every chunk submitted so far is decoded,
        stepped, and folded; re-raise the first stage failure. Markers
        flow through both stage loops even after a failure (the loops
        keep consuming), so this never hangs on a failed pipeline."""
        if self._closed:
            self._raise_if_failed()
            return
        marker = threading.Event()
        self._order_q.put(marker)
        while not marker.wait(timeout=0.25):
            if not self._drain_t.is_alive():
                break  # closed underneath us; nothing left in flight
        self._raise_if_failed()

    def close(self) -> None:
        """Stop the stage threads after the work in flight finishes and
        re-raise any latched stage failure. Idempotent."""
        if not self._closed:
            self._closed = True
            self._order_q.put(_SENTINEL)
            self._pool.shutdown(wait=True)
            self._submit_t.join(timeout=60.0)
            self._drain_t.join(timeout=60.0)
        self._raise_if_failed()

    def occupancy(self, wall_s: float) -> dict[str, float]:
        """Per-stage busy fraction of ``wall_s``, also published as
        ``overlap.<stage>_occupancy`` gauges (plus the bounded-queue
        high-water gauges)."""
        with self._busy_lock:
            busy = dict(self.busy)
        out = {}
        for stage, busy_s in busy.items():
            frac = busy_s / wall_s if wall_s > 0 else 0.0
            out[stage] = frac
            metrics.set_gauge("overlap", f"{stage}_occupancy", value=frac)
        self.publish_highwater()
        return out

    def publish_highwater(self) -> dict[str, int]:
        """Export the bounded-queue high-water marks as gauges:
        ``overlap.prepared_highwater`` (cap ``prepared_capacity``) and
        ``overlap.drain_queue_highwater`` (cap ``queue_depth``)."""
        with self._hw_lock:
            hw = dict(self.highwater)
        metrics.set_gauge("overlap", "prepared_highwater",
                          value=float(hw["prepared"]))
        metrics.set_gauge("overlap", "prepared_capacity",
                          value=float(self._max_prepared))
        metrics.set_gauge("overlap", "drain_queue_highwater",
                          value=float(hw["drain_queue"]))
        metrics.set_gauge("overlap", "drain_queue_capacity",
                          value=float(self.queue_depth))
        stage = getattr(self._sink, "staging_depths", None)
        if stage is not None:
            depths = stage()
            if depths:
                metrics.set_gauge(
                    "overlap", "staging_ring_highwater",
                    value=float(depths["staging_ring_highwater"]))
                metrics.set_gauge(
                    "overlap", "staging_ring_capacity",
                    value=float(depths["staging_ring_capacity"]))
                hw.update(depths)
        return hw

    def queue_depths(self) -> dict[str, int]:
        """Instantaneous bounded-queue depths (plus caps and high-water
        marks) — the ``/healthz`` surface for telling a decode-starved
        pipeline from a drain-starved one while it runs."""
        with self._hw_lock:
            prepared = self._prepared_in_use
            hw = dict(self.highwater)
        depths = {
            "prepared": prepared,
            "prepared_capacity": self._max_prepared,
            "prepared_highwater": hw["prepared"],
            "drain_queue": self._drain_q.qsize(),
            "drain_queue_capacity": self.queue_depth,
            "drain_queue_highwater": hw["drain_queue"],
        }
        # Staged mode adds the third bounded stage: the sink's staging
        # ring (decoded-and-staged but undispatched chunks).
        stage = getattr(self._sink, "staging_depths", None)
        if stage is not None:
            depths.update(stage())
        return depths

    # -- stage bodies ----------------------------------------------------
    def _decode_one(self, pairs):
        t0 = time.perf_counter()
        try:
            with trace.span("ingest.decode", cat="ingest",
                            entries=len(pairs)):
                return self._sink._prepare_chunk(pairs)
        finally:
            self._add_busy("decode", time.perf_counter() - t0)

    def _submit_loop(self) -> None:
        while True:
            item = self._order_q.get()
            if item is _SENTINEL:
                self._flush_sink_staging()
                self._drain_q.put(_SENTINEL)
                return
            if isinstance(item, threading.Event):  # drain_all barrier
                # A barrier covers everything SUBMITTED so far — in
                # staged mode that includes chunks parked in the sink's
                # staging ring, which must dispatch (as a padded
                # partial envelope) before the marker passes.
                self._flush_sink_staging()
                self._drain_q.put(item)
                continue
            try:
                prep = item.result()
            except BaseException as err:
                self._release_prepared()
                self._fail(err)
                continue  # keep consuming so close()/drain_all() return
            if self._failed.is_set():
                self._release_prepared()
                continue
            # Dispatch-lock wait is sampled SEPARATELY from the
            # storeCertificate envelope (its own busy bucket + the
            # dispatchLockWait sample): lock contention is not submit
            # work, and folding it in overstated the submit occupancy
            # gauge / the bench's e2e dispatch budget.
            t_lock = time.perf_counter()
            try:
                with trace.span("ingest.submit_locked", cat="ingest"), \
                        self._sink._dispatch_lock:
                    lock_s = time.perf_counter() - t_lock
                    self._add_busy("lock", lock_s)
                    metrics.add_sample("ct-fetch", "dispatchLockWait",
                                       value=lock_s)
                    t0 = time.perf_counter()
                    try:
                        with metrics.measure("ct-fetch", "storeCertificate"), \
                                trace.span("ingest.submit", cat="ingest"):
                            work = self._sink._submit_chunk(prep)
                    finally:
                        self._add_busy("submit", time.perf_counter() - t0)
            except BaseException as err:
                self._fail(err)
                continue
            finally:
                self._release_prepared()
            self._enqueue_drain(work)

    def _enqueue_drain(self, work) -> None:
        for kind, payload, der_of in work:
            self._drain_q.put((kind, payload, der_of))
            depth = self._drain_q.qsize()
            with self._hw_lock:
                if depth > self.highwater["drain_queue"]:
                    self.highwater["drain_queue"] = depth

    def _flush_sink_staging(self) -> None:
        """Dispatch whatever sits in the sink's staging ring (staged
        mode only; a sink without a ring no-ops). Runs on the submit
        thread so ring access stays serialized under the dispatch
        lock. After a latched failure the ring is left undispatched —
        the same already-decoded-work-is-dropped contract a decode
        failure applies."""
        flush = getattr(self._sink, "_flush_staging_items", None)
        if flush is None or self._failed.is_set():
            return
        try:
            with self._sink._dispatch_lock:
                work = flush()
        except BaseException as err:
            self._fail(err)
            return
        self._enqueue_drain(work)

    def _drain_loop(self) -> None:
        while True:
            item = self._drain_q.get()
            if item is _SENTINEL:
                return
            if isinstance(item, threading.Event):
                item.set()
                continue
            kind, payload, der_of = item
            t0 = time.perf_counter()
            try:
                with trace.span("ingest.drain", cat="ingest"):
                    if kind == "pending":
                        self._sink._complete_item(payload, der_of)
                    else:  # "result": oversized exact lane, already folded
                        self._sink._store_pems(payload, der_of)
            except BaseException as err:
                self._fail(err)
            finally:
                self._add_busy("drain", time.perf_counter() - t0)

    # -- shared plumbing -------------------------------------------------
    def _release_prepared(self) -> None:
        with self._hw_lock:
            self._prepared_in_use -= 1
        self._prepared_sem.release()

    def _add_busy(self, stage: str, seconds: float) -> None:
        with self._busy_lock:
            self.busy[stage] += seconds

    def _fail(self, err: BaseException) -> None:
        first = False
        with self._exc_lock:
            if self._exc is None:
                self._exc = err
                first = True
        self._failed.set()
        metrics.incr_counter("overlap", "stage_error")
        if first:
            # Latch-time post-mortem: the FIRST stage failure dumps the
            # trace ring + metric snapshots (no-op unless a flight
            # recorder is installed), so a wedged or crashed run leaves
            # an artifact even if the OverlapError never surfaces.
            trace.instant("overlap.stage_error", cat="ingest",
                          error=repr(err)[:500])
            flight.dump(f"overlap stage failure: {err!r}")

    def _raise_if_failed(self) -> None:
        if self._failed.is_set():
            raise OverlapError(
                f"overlap pipeline stage failed: {self._exc!r}"
            ) from self._exc
