"""CT log v1 HTTP API client.

Mirrors the reference's use of certificate-transparency-go's
``client.New`` + ``GetSTH`` + ``GetRawEntries``
(/root/reference/cmd/ct-fetch/ct-fetch.go:249-274,416-424):

- entries are fetched in ranges of up to 1000 per request
  (ct-fetch.go:417); the server may return fewer — callers advance by
  what they got, and the client remembers the server's observed page
  size so later windows ask for what the log actually serves (real
  logs cap get-entries far below the spec maximum);
- HTTP 429 AND transient 5xx (500/502/503/504 — real logs shed load
  with these at least as often as with 429) trigger a jittered
  exponential backoff of 500 ms – 5 min and a retry of the same range
  (ct-fetch.go:409-437), honoring Retry-After when present; retries
  are counted under ``ingest.retry.*`` by status;
- other HTTP errors raise and are handled by the caller's
  log-level error policy.

The transport is injectable — ``transport(url) -> (status, headers,
body)`` — so tests and the zero-egress benchmark environment can serve
synthetic logs without sockets; the default uses urllib.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from dataclasses import dataclass
from typing import Callable, Optional

from ct_mapreduce_tpu.telemetry.metrics import incr_counter, measure
from ct_mapreduce_tpu.utils.backoff import JitteredBackoff

BATCH_SIZE = 1000  # entries per get-entries request (ct-fetch.go:417)

# Statuses retried with backoff instead of raised: rate limiting plus
# the transient 5xx family production logs answer under load.
RETRYABLE_STATUSES = frozenset({429, 500, 502, 503, 504})

Transport = Callable[[str], tuple[int, dict, bytes]]


def short_url(url: str) -> str:
    """Log URL without scheme or trailing slash — the reference's
    ShortURL identity (storage/types.go checkpoint keying)."""
    for prefix in ("https://", "http://"):
        if url.startswith(prefix):
            url = url[len(prefix) :]
            break
    return url.rstrip("/")


def _urllib_transport(url: str) -> tuple[int, dict, bytes]:
    req = urllib.request.Request(
        url, headers={"User-Agent": "ct-mapreduce-tpu/0.1"}
    )
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as err:
        return err.code, dict(err.headers or {}), err.read()


@dataclass
class SignedTreeHead:
    tree_size: int
    timestamp_ms: int
    sha256_root_hash: str = ""
    tree_head_signature: str = ""


@dataclass
class RawEntry:
    index: int
    leaf_input: str  # base64, as served
    extra_data: str


class CTClientError(RuntimeError):
    def __init__(self, url: str, status: int, body: bytes):
        super().__init__(f"HTTP {status} from {url}: {body[:200]!r}")
        self.status = status


class CTLogClient:
    """One CT log endpoint, normalized to ``https://`` when no scheme
    is given (the reference's config takes full URLs)."""

    def __init__(
        self,
        log_url: str,
        transport: Optional[Transport] = None,
        sleep: Callable[[float], None] = time.sleep,
        max_retries: int = 100,
    ):
        if "://" not in log_url:
            log_url = "https://" + log_url
        self.log_url = log_url.rstrip("/")
        self.short_url = short_url(log_url)
        self.transport = transport or _urllib_transport
        self.sleep = sleep
        self.max_retries = max_retries
        # Adaptive get-entries window: starts at the spec maximum and
        # clamps down to the page size the server actually returns.
        self.page_size = BATCH_SIZE

    # -- plumbing --------------------------------------------------------
    def _get_json(self, path: str) -> dict:
        url = f"{self.log_url}/ct/v1/{path}"
        backoff = JitteredBackoff(min_s=0.5, max_s=300.0)
        status = 429
        for _ in range(self.max_retries):
            status, headers, body = self.transport(url)
            if status == 200:
                return json.loads(body)
            if status in RETRYABLE_STATUSES:
                # ct-fetch.go:426-437: jittered 500ms-5min, honor
                # Retry-After seconds when the server sends one. 5xx
                # takes the exact same lane — a 503 from an overloaded
                # log is rate limiting by another name.
                if status == 429:
                    incr_counter("LogWorker", self.short_url, "429")
                incr_counter("ingest", "retry", str(status))
                retry_after = next(
                    (v for k, v in headers.items()
                     if k.lower() == "retry-after"),
                    None,
                )
                if retry_after:
                    try:
                        # Clamp to the 500ms-5min window — a hostile value
                        # must neither stall the downloader for hours nor
                        # turn the retry loop into a zero-delay hammer.
                        delay = min(max(float(retry_after), backoff.min_s),
                                    backoff.max_s)
                    except ValueError:
                        delay = backoff.duration()
                else:
                    delay = backoff.duration()
                self.sleep(delay)
                continue
            raise CTClientError(url, status, body)
        incr_counter("ingest", "retry", "giveup")
        raise CTClientError(url, status, b"retry budget exhausted")

    # -- API -------------------------------------------------------------
    def get_sth(self) -> SignedTreeHead:
        with measure("LogWorker", self.short_url, "getSTH"):
            obj = self._get_json("get-sth")
        return SignedTreeHead(
            tree_size=int(obj["tree_size"]),
            timestamp_ms=int(obj.get("timestamp", 0)),
            sha256_root_hash=obj.get("sha256_root_hash", ""),
            tree_head_signature=obj.get("tree_head_signature", ""),
        )

    def get_raw_entries(self, start: int, end: int) -> list[RawEntry]:
        """Entries ``[start, end]`` inclusive, like ct-go's
        GetRawEntries; the server may truncate the range. The first
        truncated response clamps this client's window to the page
        size the server demonstrated, so every later request asks for
        exactly what the log serves instead of re-discovering the cap
        one oversized range at a time."""
        if end < start:
            return []
        end = min(end, start + self.page_size - 1)
        with measure("LogWorker", self.short_url, "getRawEntries"):
            obj = self._get_json(f"get-entries?start={start}&end={end}")
        entries = obj.get("entries", [])
        if 0 < len(entries) < end - start + 1:
            # Short page on a full-window ask: adopt the server's size.
            if len(entries) < self.page_size:
                self.page_size = len(entries)
                incr_counter("ingest", "window_clamp")
        return [
            RawEntry(
                index=start + i,
                leaf_input=e["leaf_input"],
                extra_data=e.get("extra_data", ""),
            )
            for i, e in enumerate(entries)
        ]

    def get_entry_and_proof(self, index: int, tree_size: int) -> dict:
        """ct-getcert's fetch path (get-entry-and-proof)."""
        return self._get_json(
            f"get-entry-and-proof?leaf_index={index}&tree_size={tree_size}"
        )
