"""The ingest runtime: per-log downloaders feeding store workers.

Rebuilds the reference's ``LogSyncEngine`` / ``LogWorker`` /
``insertCTWorker`` machinery (/root/reference/cmd/ct-fetch/
ct-fetch.go:83-488) on Python threads and a bounded queue:

- one downloader thread per log URL (ct-fetch.go:527-565), fetching
  ranges of 1000 and decoding leaves (ct-fetch.go:398-488);
- a shared bounded entry queue, capacity 16,384 (ct-fetch.go:132);
- ``num_threads`` store workers draining the queue into a sink
  (ct-fetch.go:140-145,180-246);
- a save ticker checkpointing each log's cursor every ``save_period``
  and at exit (ct-fetch.go:307-312,360-392,472-473);
- graceful stop: signal → downloaders drain → queue drains → workers
  join → final state save (ct-fetch.go:610-620).

Two sinks cover the reference path and the TPU path:

- :class:`DatabaseSink` — per-entry host store through
  ``FilesystemDatabase`` with the ``certIsFilteredOut`` semantics
  (ct-fetch.go:44-70): reference-parity mode.
- :class:`AggregatorSink` — packs entries into device batches for
  :class:`~ct_mapreduce_tpu.agg.aggregator.TpuAggregator`: the
  TPU-native mode, where filtering happens on device.
"""

from __future__ import annotations

import contextlib
import queue
import random
import threading
import time
from collections import deque

import numpy as np
from dataclasses import dataclass, field
from datetime import datetime, timezone
from typing import Optional, Protocol

from ct_mapreduce_tpu.core import der as hostder
from ct_mapreduce_tpu.core.types import CertificateLog
from ct_mapreduce_tpu.ingest.ctclient import BATCH_SIZE, CTLogClient
from ct_mapreduce_tpu.ingest.leaf import (
    DecodedEntry,
    LeafDecodeError,
    decode_json_entry,
    leaf_timestamp_ms as decode_leaf_timestamp,
)
from ct_mapreduce_tpu.config import profile as platprofile
from ct_mapreduce_tpu.telemetry import metrics, trace

ENTRY_QUEUE_CAPACITY = 16384  # ct-fetch.go:132

_STAGING_KNOBS = (
    platprofile.Knob("chunksPerDispatch", "CTMR_CHUNKS_PER_DISPATCH", 1,
                     parse=int, is_set=platprofile.pos_int,
                     post=lambda v: max(1, int(v))),
    platprofile.Knob("stagingDepth", "CTMR_STAGING_DEPTH", 2,
                     parse=int, is_set=platprofile.pos_int,
                     post=lambda v: max(1, int(v))),
)


def resolve_staging(chunks_per_dispatch: int = 0,
                    staging_depth: int = 0) -> tuple[int, int]:
    """Resolve the staged-device-queue knobs through the shared
    platformProfile ladder (config/profile.py): explicit value (config
    directive / kwarg) > ``CTMR_CHUNKS_PER_DISPATCH`` /
    ``CTMR_STAGING_DEPTH`` env > profile ``knobs.staging`` > defaults
    (K=1 — legacy per-chunk dispatch; depth 2 — double buffer).
    Unparseable env values are ignored, matching the config layer's
    tolerance."""
    r = platprofile.resolve_section("staging", _STAGING_KNOBS, {
        "chunksPerDispatch": int(chunks_per_dispatch or 0),
        "stagingDepth": int(staging_depth or 0),
    })
    return r["chunksPerDispatch"], r["stagingDepth"]


def _resolve_verify_lazy(flag, keys_path, window=None, qtable_size=0):
    """Import-light wrapper around ``verify.lane.resolve_verify`` —
    the verify package (and with it the ECDSA kernels) only loads when
    the lane could actually be on."""
    import os

    if flag is None:
        flag = os.environ.get("CTMR_VERIFY", "0") == "1"
    if not flag:
        return False, "", 0, 0, 0
    from ct_mapreduce_tpu.verify.lane import resolve_verify

    return resolve_verify(True, keys_path, window=window,
                          qtable_size=qtable_size)


class EntrySink(Protocol):
    def store(self, entry: DecodedEntry, log_url: str) -> None: ...
    def flush(self) -> None: ...


class DatabaseSink:
    """Per-entry host store: parse → filter → ``database.store``.

    The filter reproduces ``certIsFilteredOut`` (ct-fetch.go:44-70):
    CA certs out, expired out unless ``log_expired_entries``, and when
    CN prefixes are configured, issuers whose CN matches none are out.
    """

    def __init__(
        self,
        database,
        cn_filters: tuple[str, ...] = (),
        log_expired_entries: bool = False,
        now: Optional[datetime] = None,
    ):
        self.database = database
        self.cn_filters = tuple(cn_filters)
        self.log_expired_entries = log_expired_entries
        self._fixed_now = now

    def _filtered_out(self, fields) -> bool:
        if fields.is_ca:
            metrics.incr_counter("ct-fetch", "certIsFilteredOut", "CA")
            return True
        now = self._fixed_now or datetime.now(timezone.utc)
        if not self.log_expired_entries and fields.not_after < now:
            metrics.incr_counter("ct-fetch", "certIsFilteredOut", "expired")
            return True
        if self.cn_filters and not any(
            fields.issuer_cn.startswith(p) for p in self.cn_filters
        ):
            metrics.incr_counter("ct-fetch", "certIsFilteredOut", "cn")
            return True
        return False

    def store(self, entry: DecodedEntry, log_url: str) -> None:
        try:
            with metrics.measure("ct-fetch", "parseCertificate"):
                fields = hostder.parse_cert(entry.cert_der)
        except Exception:
            # Tolerate-and-skip, like ct-fetch.go:206-215.
            metrics.incr_counter("ct-fetch", "parseCertificateError")
            return
        if self._filtered_out(fields):
            return
        if entry.issuer_der is None:
            metrics.incr_counter("ct-fetch", "noChainError")
            return
        with metrics.measure("ct-fetch", "storeCertificate"):
            self.database.store(
                entry.cert_der, entry.issuer_der, log_url, entry.index
            )
        metrics.incr_counter("ct-fetch", "insertCertificate")

    def flush(self) -> None:
        pass


class AggregatorSink:
    """Batches entries for the device pipeline.

    Entries accumulate host-side until ``flush_size`` and are then
    dispatched in one ``TpuAggregator.ingest`` call (parse, filter,
    fingerprint, dedup and counts all happen on device). A lock
    serializes dispatch — the aggregator's table state is donated
    between steps, so one device stream exists regardless of how many
    store workers feed it.
    """

    PAD_LEN = 2048  # device row width for the raw path (bucket; certs
    # above it take the exact host lane, like oversized serials)

    def __init__(self, aggregator, flush_size: int = 4096, backend=None,
                 device_queue_depth: int = 2, decode_workers: int = 0,
                 overlap_workers: int = 0, preparsed: Optional[bool] = None,
                 decode_threads: int = 0, chunks_per_dispatch: int = 0,
                 staging_depth: int = 0,
                 verify_signatures: Optional[bool] = None,
                 verify_log_keys: Optional[str] = None,
                 verify_precomp_window: Optional[int] = None,
                 verify_qtable_size: int = 0):
        self.aggregator = aggregator
        self.flush_size = flush_size
        # Optional durable backend (certPath): first-seen certs get the
        # same <exp>/<issuer>/<serial> PEM tree + dirty markers the
        # reference writes (filesystemdatabase.go:189-208).
        self.backend = backend
        self._allocated: set[tuple[str, str]] = set()
        self._pem_lock = threading.Lock()  # overlap drains from a thread
        self._pending: list[tuple[bytes, bytes]] = []
        self._pending_raw: list[tuple[str, str]] = []
        self._lock = threading.Lock()
        self._dispatch_lock = threading.Lock()  # one device stream
        # Host↔device pipelining (deviceQueueDepth, SURVEY §2.2 PP row;
        # the reference overlaps download and store with goroutines + a
        # 16,384-slot channel, ct-fetch.go:132,398-488): device steps
        # are SUBMITTED without readback and consumed once more than
        # `device_queue_depth` batches are in flight, so decode of
        # batch N+1 overlaps the device step of batch N. Depth 0 =
        # fully synchronous (reference-exact store ordering).
        self.device_queue_depth = max(0, int(device_queue_depth))
        # 0 = leafpack auto-sizing (CTMR_DECODE_WORKERS / cpu count).
        self.decode_workers = int(decode_workers) or None
        # Intra-chunk native decode threads (`decodeThreads` directive /
        # CTMR_DECODE_THREADS): the persistent C++ worker pool splits
        # each chunk's decode, row pack, and sidecar extraction over
        # lane ranges. 0 = leafpack auto (env, then cpu count). This is
        # the knob that makes ONE chunk's host feed scale with cores;
        # `overlapWorkers` pipelines ACROSS chunks on top of it
        # (workers × threads should stay ≤ host cores, see
        # ingest/overlap.py).
        self.decode_threads = int(decode_threads) or None
        self._inflight: deque = deque()  # (PendingIngest, der_of)
        # Without a PEM backend the per-entry serial bytes are only
        # needed for the cross-encoding guard; let the aggregator skip
        # materializing them when it can (count-only fast path). A
        # filter capture (round 15) needs the bytes regardless of PEM
        # backing — never clobber its want_serials.
        aggregator.want_serials = (
            backend is not None
            or getattr(aggregator, "filter_capture", None) is not None)
        self.entries_in = 0
        # Overlapped ingest (overlapWorkers > 0): raw chunks route
        # through a three-stage scheduler — decode pool ‖ ordered
        # device submit ‖ bounded drain consumer — instead of the
        # caller-thread decode→submit→drain sequence above. Exact
        # same decode/submit/complete primitives, so results are
        # parity-identical; only the threading changes.
        # Pre-parsed ingest lane (CTMR_PREPARSED=1 / preparsedIngest
        # directive): the native decoder's sidecar extraction replaces
        # the on-device DER walk — the device step runs fingerprint +
        # insert + counts on ~59 B/lane of compact inputs, row bytes
        # never ship, and the readback is the compact bitmask/flag-id
        # form. Lanes the extractor flags undecidable (sidecar.ok == 0)
        # replay through the device-walker path, so the two lanes stay
        # parity-exact including host-lane spill counts. Requires the
        # native library; silently stays on the walker lane without it.
        if preparsed is None:
            import os

            preparsed = os.environ.get("CTMR_PREPARSED", "0") == "1"
        self.preparsed = bool(preparsed)
        # Staged device queue (round 11): `chunksPerDispatch` (K) > 1
        # routes walker-lane chunks through a staging ring — K decoded
        # chunks stack into one pinned host buffer, ship in ONE H2D
        # put, and run as ONE resident K-chunk device envelope
        # (pipeline.staged_core), dividing the per-dispatch Python +
        # readback toll by K. `stagingDepth` bounds envelopes that are
        # submitted-but-unfolded (the double-buffer depth). Explicit
        # kwarg > CTMR_CHUNKS_PER_DISPATCH / CTMR_STAGING_DEPTH env >
        # defaults (K=1 → legacy per-chunk dispatch; depth 2).
        self.chunks_per_dispatch, self.staging_depth = resolve_staging(
            chunks_per_dispatch, staging_depth)
        self._staging: list[_PreparedChunk] = []  # the ring (FIFO)
        self._staging_hw = 0  # high-water occupancy
        self._staging_bufs: dict[tuple, tuple] = {}  # (K,B,L) → (bufs, idx)
        # Signature-verification lane (round 13): `verifySignatures`
        # directive / CTMR_VERIFY env. Each decoded chunk additionally
        # runs the native SCT extraction pass; P-256-keyed SCTs batch
        # onto the device ECDSA kernel (ops/ecdsa.py) alongside the
        # dedup dispatch, undecidable lanes replay through the pure-
        # python host verifier — the walker-fallback pattern applied
        # to verification. Verdicts fold into the aggregator's per-
        # issuer verified/failed vectors. Off by default: the lane adds
        # an extraction pass + a second kernel family to the hot path.
        # Round 17: `verifyPrecompWindow` (0 = legacy Jacobian ladder)
        # selects the windowed-precompute kernels and `verifyQTableSize`
        # bounds the per-curve device-resident per-log-key Q-table LRU.
        v_on, v_keys, v_batch, v_window, v_qsize = _resolve_verify_lazy(
            verify_signatures, verify_log_keys,
            verify_precomp_window, verify_qtable_size)
        self.verifier = None
        if v_on:
            from ct_mapreduce_tpu.verify.lane import (
                LogKeyRegistry,
                SignatureVerifier,
            )

            keys = (LogKeyRegistry.from_json_file(v_keys) if v_keys
                    else LogKeyRegistry())
            self.verifier = SignatureVerifier(
                aggregator, keys, batch_width=v_batch,
                window=v_window, qtable_size=v_qsize)
        self.overlap_workers = max(0, int(overlap_workers))
        self._overlap = None
        if self.overlap_workers:
            from ct_mapreduce_tpu.ingest.overlap import OverlapIngestPipeline

            self._overlap = OverlapIngestPipeline(
                self, decode_workers=self.overlap_workers,
                # In staged mode the drain bound counts ENVELOPES, and
                # stagingDepth is that double-buffer depth.
                queue_depth=(self.staging_depth
                             if self.chunks_per_dispatch > 1
                             else max(1, self.device_queue_depth)),
            )

    def store(self, entry: DecodedEntry, log_url: str) -> None:
        if entry.issuer_der is None:
            metrics.incr_counter("ct-fetch", "noChainError")
            return
        batch: Optional[list[tuple[bytes, bytes]]] = None
        with self._lock:
            self._pending.append((entry.cert_der, entry.issuer_der))
            self.entries_in += 1
            if len(self._pending) >= self.flush_size:
                batch, self._pending = self._pending, []
        if batch:
            self._dispatch(batch)

    def store_raw_batch(self, raw: "RawBatch") -> None:
        """Accumulate an undecoded get-entries response; decoded and
        dispatched natively in flush-size chunks."""
        pairs = list(zip(raw.leaf_inputs, raw.extra_datas))
        chunk: Optional[list[tuple[str, str]]] = None
        with self._lock:
            self._pending_raw.extend(pairs)
            self.entries_in += len(pairs)
            if len(self._pending_raw) >= self.flush_size:
                chunk, self._pending_raw = self._pending_raw, []
        if chunk:
            self._dispatch_raw(chunk)

    def _dispatch_raw(self, pairs: list[tuple[str, str]]) -> None:
        if self._overlap is not None:
            # Overlapped mode: the chunk enters the three-stage
            # scheduler; decode happens on its pool, submission on its
            # ordered submit thread, completion on its drain consumer.
            self._overlap.submit_chunk(pairs)
            return
        with trace.span("ingest.decode", cat="ingest", entries=len(pairs)):
            prep = self._prepare_chunk(pairs)
        t_lock = time.monotonic()
        with trace.span("ingest.submit_locked", cat="ingest"), \
                self._dispatch_lock:
            # Lock wait sampled apart from the storeCertificate
            # envelope (see ingest/overlap.py's submit loop): multiple
            # store workers contend here, and the wait is not submit
            # work.
            metrics.add_sample("ct-fetch", "dispatchLockWait",
                               value=time.monotonic() - t_lock)
            with metrics.measure("ct-fetch", "storeCertificate"), \
                    trace.span("ingest.submit", cat="ingest"):
                self._dispatch_prepared(prep)

    def _dispatch_prepared(self, prep: "_PreparedChunk") -> None:
        for item in self._submit_chunk(prep):
            if item[0] == "pending":
                self._inflight.append((item[1], item[2]))
            else:  # oversized-lane result: fold PEMs immediately
                self._store_pems(item[1], item[2])
        # Staged mode counts in-flight ENVELOPES against stagingDepth
        # (the double-buffer bound); the legacy per-chunk path keeps
        # deviceQueueDepth semantics.
        self._drain_inflight(self.staging_depth
                             if self.chunks_per_dispatch > 1
                             else self.device_queue_depth)

    def _prepare_chunk(self, pairs: list[tuple[str, str]]) -> "_PreparedChunk":
        """Stage 1 — decode + pack + H2D submit, NO aggregator-state
        mutation beyond the (thread-safe) issuer registry: safe to run
        on any thread, concurrently with device work and drains."""
        from ct_mapreduce_tpu.ingest.leaf import LeafDecodeError, decode_entry
        from ct_mapreduce_tpu.native import leafpack

        lis = [p[0] for p in pairs]
        eds = [p[1] for p in pairs]
        # Row-width bucketing, now BEFORE the decode: the decoder's
        # allocation+memset scale with the pad (measured +47% decode
        # time at 2048 vs 1024 for 2^20-entry batches), and base64
        # length exactly upper-bounds the decoded leaf_input — so a
        # batch whose every leaf_input provably fits the narrow width
        # decodes straight into narrow rows. Precert entries pack
        # their cert from extra_data (not bounded by leaf_input), so
        # any TOO_LONG status triggers one full-width redecode — rare,
        # and statuses/lengths are recomputed so semantics are
        # unchanged.
        narrow = self.PAD_LEN // 2
        pad = self.PAD_LEN
        if narrow >= 512:
            max_li_raw = max((len(s) for s in lis), default=0) * 3 // 4
            if max_li_raw + 64 <= narrow:
                pad = narrow
        t_dec = time.monotonic()
        with metrics.measure("ct-fetch", "decodeBatch"):
            dec = leafpack.decode_raw_batch(
                lis, eds, pad, workers=self.decode_workers,
                threads=self.decode_threads,
            )
            if (pad < self.PAD_LEN
                    and bool((dec.status == leafpack.TOO_LONG).any())):
                pad = self.PAD_LEN
                dec = leafpack.decode_raw_batch(
                    lis, eds, pad, workers=self.decode_workers,
                    threads=self.decode_threads,
                )
        # Host-feed observability: the resolved intra-chunk thread
        # count (gauge) and this chunk's decode cost (ns/entry sample)
        # — the two numbers that say whether the feed is scaling.
        if len(lis):
            metrics.set_gauge(
                "ingest", "decode_threads",
                value=float(leafpack.resolve_threads(
                    len(lis), self.decode_threads or self.decode_workers)))
            metrics.add_sample(
                "ingest", "decode_ns_per_entry",
                value=(time.monotonic() - t_dec) / len(lis) * 1e9)
        # When the batch decoded wide but every cert fits half the
        # pad, ship the narrow view — H2D bytes halve (the dominant
        # cost on tunneled links), at the price of one extra compiled
        # step variant.
        data = dec.data
        if (narrow >= 512 and data.shape[1] > narrow
                and dec.length.max(initial=0) <= narrow):
            data = data[:, :narrow]

        n = len(pairs)
        issuer_idx = np.zeros((n,), np.int32)
        oversized: list[tuple[bytes, bytes]] = []
        # Every DecodedBatch producer computes issuer groups
        # (leafpack.decode_raw_batch native/threaded/python paths); a
        # third-party producer that omits them violates the contract.
        # Not an assert: stripped under `python -O` the failure would
        # surface as an opaque TypeError below.
        if dec.issuer_group is None:
            raise ValueError(
                "DecodedBatch producer did not compute issuer groups "
                "(issuer_group/group_issuers are required)")
        # Vectorized bookkeeping: per-GROUP registry work (a handful of
        # distinct issuers per batch), numpy for the per-entry mapping
        # — no 64K-iteration Python loop.
        gmap = np.full((len(dec.group_issuers) + 1,), -1, np.int32)
        for g, der in enumerate(dec.group_issuers):
            try:
                gmap[g] = self.aggregator.registry.get_or_assign(der)
            except Exception:
                # Malformed issuer DER costs its entries, not the
                # whole chunk (per-entry path parity).
                gmap[g] = -1
        ok = dec.status == leafpack.OK
        grp = dec.issuer_group
        mapped = gmap[grp]  # grp -1 → last slot (-1 sentinel)
        valid = ok & (mapped >= 0)
        issuer_idx[valid] = mapped[valid]
        bad_issuer = int((ok & (mapped < 0)).sum())
        no_chain = int((dec.status == leafpack.NO_CHAIN).sum())
        # Both oversize flavors take the exact per-entry lane; only
        # cert-exceeds-pad (TOO_LONG) ever warranted the full-width
        # redecode above — issuer-oversize (ISSUER_TOO_LONG) certs
        # packed fine and a wider row cannot change their status.
        too_long = np.nonzero(
            (dec.status == leafpack.TOO_LONG)
            | (dec.status == leafpack.ISSUER_TOO_LONG))[0]
        other_bad = int(
            ((dec.status != leafpack.OK)
             & (dec.status != leafpack.NO_CHAIN)
             & (dec.status != leafpack.TOO_LONG)
             & (dec.status != leafpack.ISSUER_TOO_LONG)).sum()
        )
        if bad_issuer or other_bad:
            metrics.incr_counter("ct-fetch", "parseLeafError",
                                 value=float(bad_issuer + other_bad))
        if no_chain:
            metrics.incr_counter("ct-fetch", "noChainError",
                                 value=float(no_chain))
        for i in too_long:
            # Rare oversized cert: exact per-entry lane.
            try:
                import base64

                e = decode_entry(
                    int(i), base64.b64decode(lis[i]),
                    base64.b64decode(eds[i] or "")
                )
            except LeafDecodeError:
                metrics.incr_counter("ct-fetch", "parseLeafError")
                continue
            if e.issuer_der is None:
                metrics.incr_counter("ct-fetch", "noChainError")
            else:
                oversized.append((e.cert_der, e.issuer_der))

        # Signature-verification lane: one more native pass over the
        # packed rows extracts embedded-SCT tuples. Runs on the decode
        # stage (overlap-friendly); classification and dispatch happen
        # at submit time under the dispatch lock. The eligible set is
        # the decoded-OK + issuer-mapped lanes BEFORE the sidecar
        # split below — walker-fallback lanes still carry auditable
        # SCTs. (Oversized certs never reach packed rows; their rare
        # SCTs are not audited — an honest gap, counted nowhere.)
        scts = None
        verify_eligible = None
        if self.verifier is not None:
            from ct_mapreduce_tpu.native import leafpack as _lp
            from ct_mapreduce_tpu.verify import sct as _sctlib

            # RFC 6962 precert digests sign the per-lane
            # issuer_key_hash: SHA-256 of the chain issuer's SPKI,
            # computed once per issuer GROUP (a handful per batch) and
            # broadcast per lane; lanes without a mapped issuer hash
            # as all-zero and can only verify against fixture SCTs
            # signed the same way.
            ikh_groups = np.zeros((len(dec.group_issuers) + 1, 32),
                                  np.uint8)
            for g, der in enumerate(dec.group_issuers):
                ikh_groups[g] = np.frombuffer(
                    _sctlib.issuer_key_hash_of(der), np.uint8)
            lane_ikh = ikh_groups[np.where(valid, grp, -1)]
            scts = _lp.extract_scts(
                data, dec.length,
                threads=self.decode_threads or self.decode_workers,
                issuer_key_hash=lane_ikh)
            verify_eligible = valid.copy()

        # Pre-parsed lane: extract walker-exact sidecars on the host
        # (one more native pass over the just-packed rows — cache-warm)
        # and split undecidable lanes out for the device-walker replay.
        sidecar = None
        walker_fallback: list[tuple[bytes, bytes]] = []
        if self.preparsed:
            sidecar = leafpack.extract_sidecars(
                data, dec.length,
                threads=self.decode_threads or self.decode_workers)
            if sidecar is not None:
                pre_ok = sidecar.ok.astype(bool)
                for i in np.nonzero(valid & ~pre_ok)[0]:
                    # Rare walker-undecidable lane: replay through the
                    # device-walker path (aggregator.ingest), exactly
                    # what the default lane would do with it.
                    walker_fallback.append((
                        data[i, : dec.length[i]].tobytes(),
                        dec.group_issuers[int(dec.issuer_group[i])],
                    ))
                valid = valid & pre_ok

        # Start the H2D transfer of the big byte rows BEFORE taking the
        # dispatch lock: device_put enqueues asynchronously, so the
        # transfer of batch N+1 overlaps the device step of batch N
        # (the decode half of the overlap comes from the decode stage
        # running ahead of the submit stage). Small arrays stay
        # host-side — the aggregator reads them for bookkeeping. Tail
        # chunks (not a multiple of the compiled batch shape) take the
        # NumPy path: their padding copy happens host-side in the
        # aggregator. The pre-parsed lane never transfers rows at all
        # (its device inputs are the compact per-lane fields).
        data_host = data
        if (sidecar is None and valid.any()
                and self.chunks_per_dispatch <= 1
                and data.shape[0] % self.aggregator.batch_size == 0):
            # Staged mode skips the per-chunk put: the staging ring
            # ships the stacked [K, B, L] buffer in one H2D instead.
            import jax

            # Timing note: device_put ENQUEUES asynchronously, so this
            # sample is submit cost; the transfer itself overlaps the
            # previous step and any residual lands in completeBatch.
            with metrics.measure("ct-fetch", "h2dSubmit"):
                data = jax.device_put(data)
        return _PreparedChunk(
            data=data, host_data=data_host, length=dec.length,
            issuer_idx=issuer_idx, valid=valid, dec=dec,
            oversized=oversized, sidecar=sidecar,
            walker_fallback=walker_fallback,
            scts=scts, verify_eligible=verify_eligible,
        )

    def _submit_verify(self, prep: "_PreparedChunk") -> None:
        """Route one prepared chunk's SCT lanes into the verify lane.
        Caller holds ``_dispatch_lock`` (the verifier shares the one
        device stream with the dedup dispatch)."""
        if self.verifier is None or prep.scts is None:
            return
        self.verifier.submit_chunk(
            prep.scts, prep.issuer_idx, prep.verify_eligible,
            prep.host_data, prep.length,
        )

    # -- staged device queue (round 11) ----------------------------------
    def _submit_staged(self, prep: "_PreparedChunk") -> list[tuple]:
        """Staged walker lane: enqueue the prepared chunk into the
        staging ring; every K chunks the ring stacks into one pinned
        host buffer, ships in ONE H2D put, and dispatches as ONE
        resident K-chunk envelope. Caller holds ``_dispatch_lock`` (the
        ring is only ever touched under it)."""
        items: list[tuple] = []
        ring = self._staging
        # Ring chunks must share a row width (the narrow/wide
        # pre-decode bucketing can alternate): a mismatch flushes
        # what's staged before the new chunk enters.
        if ring and prep.valid.any() and (
                ring[0].host_data.shape[1] != prep.host_data.shape[1]):
            items += self._flush_staging_items()
        # Chunks carrying host-exact entries (oversized certs, rare
        # walker-undecidable sidecar lanes) dispatch immediately:
        # ring-flush → stage → flush again, so the serial path's
        # intra-chunk order (device lanes, then fallback, then
        # oversized) — and with it the dedup attribution — is
        # preserved exactly.
        host_exact = bool(prep.oversized or prep.walker_fallback)
        if host_exact:
            items += self._flush_staging_items()
        if prep.valid.any():
            ring.append(prep)
            depth = len(ring)
            if depth > self._staging_hw:
                self._staging_hw = depth
            metrics.set_gauge("ingest", "staging_ring", value=float(depth))
            if host_exact or depth >= self.chunks_per_dispatch:
                items += self._flush_staging_items()
        if prep.walker_fallback:
            fb = prep.walker_fallback
            res_fb = self.aggregator.ingest(fb)
            items.append(("result", res_fb, lambda pos, _o=fb: _o[pos][0]))
        if prep.oversized:
            oversized = prep.oversized
            res_over = self.aggregator.ingest(oversized)
            items.append((
                "result", res_over, lambda pos, _o=oversized: _o[pos][0],
            ))
        metrics.incr_counter(
            "ct-fetch", "insertCertificate",
            value=float(int(prep.valid.sum()) + len(prep.oversized)
                        + len(prep.walker_fallback)),
        )
        return items

    def _staging_buffer(self, k: int, b: int, width: int) -> np.ndarray:
        """One of the cycled pinned host staging buffers for this
        envelope shape. ``stagingDepth`` bounds envelopes in flight, so
        ``stagingDepth + 2`` buffers guarantee a buffer is only reused
        after the envelope that shipped from it has been folded (its
        transfer long since complete)."""
        key = (k, b, width)
        bufs, idx = self._staging_bufs.get(key, ([], -1))
        if len(bufs) < self.staging_depth + 2:
            bufs.append(np.zeros((k, b, width), np.uint8))
            idx = len(bufs) - 1
        else:
            idx = (idx + 1) % len(bufs)
        self._staging_bufs[key] = (bufs, idx)
        return bufs[idx]

    def _flush_staging_items(self) -> list[tuple]:
        """Dispatch the staging ring as one resident envelope (no-op on
        an empty ring). Caller holds ``_dispatch_lock``. A partial ring
        (final flush, host-exact chunk, shape change) pads the K axis
        with all-invalid chunks so the envelope keeps its compiled
        shape."""
        ring, self._staging = self._staging, []
        if not ring:
            return []
        k_env = self.chunks_per_dispatch
        k_real = len(ring)
        b = max(p.host_data.shape[0] for p in ring)
        width = ring[0].host_data.shape[1]
        agg = self.aggregator
        # The mesh-sharded step routes rows host-side (staged_h2d is
        # False there): it keeps the stacked rows on host, so the
        # buffer must be fresh per envelope, not a recycled one.
        reuse = getattr(agg, "staged_h2d", True)
        buf = (self._staging_buffer(k_env, b, width) if reuse
               else np.zeros((k_env, b, width), np.uint8))
        length = np.zeros((k_env, b), np.int32)
        issuer_idx = np.zeros((k_env, b), np.int32)
        valid = np.zeros((k_env, b), bool)
        host_chunks: list[np.ndarray] = []
        for k, p in enumerate(ring):
            n_k = p.host_data.shape[0]
            buf[k, :n_k] = p.host_data
            # Stale rows past n_k (buffer reuse) are harmless — their
            # lanes stay invalid and the fold never reads them.
            length[k, :n_k] = p.length
            issuer_idx[k, :n_k] = p.issuer_idx
            valid[k, :n_k] = p.valid
            host_chunks.append(p.host_data)
        metrics.set_gauge("ingest", "staging_ring", value=0.0)
        metrics.add_sample("ingest", "dispatch_chunks", value=float(k_real))
        data = buf
        if reuse:
            import jax

            # H2D of the whole envelope, enqueued BEFORE the dispatch:
            # device_put is asynchronous on accelerator backends, so
            # this transfer rides alongside the previous envelope's
            # compute; block_until_ready never runs on the submit side.
            with trace.span("ingest.h2d", cat="ingest", chunks=k_real,
                            bytes=int(buf.nbytes)), \
                    metrics.measure("ct-fetch", "h2dSubmit"):
                data = jax.device_put(buf)
            metrics.incr_counter("ingest", "h2d_bytes",
                                 value=float(buf.nbytes))
        pending = agg.ingest_staged_submit(
            data, length, issuer_idx, valid, host_chunks)
        decs = [p.dec for p in ring]

        def der_of(pos, _decs=decs, _b=b):
            k, j = divmod(pos, _b)
            d = _decs[k]
            return d.data[j, : d.length[j]].tobytes()

        return [("pending", pending, der_of)]

    def staging_depths(self) -> dict[str, int]:
        """Staging-ring occupancy for ``/healthz`` (merged into the
        overlap pipeline's ``queue_depths``): a ring pinned below K
        while the drain is saturated is the drain-starvation signature
        the prepared/drain gauges alone can't show."""
        if self.chunks_per_dispatch <= 1:
            return {}
        return {
            "staging_ring": len(self._staging),
            "staging_ring_capacity": self.chunks_per_dispatch,
            "staging_ring_highwater": self._staging_hw,
        }

    def _submit_chunk(self, prep: "_PreparedChunk") -> list[tuple]:
        """Stage 2 — dispatch the device step(s) for a prepared chunk.
        Caller MUST hold ``_dispatch_lock`` (one device stream; the
        donated table state serializes submissions). Returns drain
        items: ``("pending", PendingIngest, der_of)`` entries whose
        ``complete()`` is stage 3, and ``("result", IngestResult,
        der_of)`` entries (the rare oversized exact lane, already
        complete) that only need PEM folding.

        With ``chunksPerDispatch`` > 1 the walker lane detours through
        the staging ring (``_submit_staged``): a chunk may return no
        drain items (staged, awaiting ring mates) or one pending
        covering a whole K-chunk envelope."""
        self._submit_verify(prep)
        if self.chunks_per_dispatch > 1 and prep.sidecar is None:
            return self._submit_staged(prep)
        items: list[tuple] = []
        if prep.valid.any():
            if prep.sidecar is not None:
                pending = self.aggregator.ingest_preparsed_submit(
                    prep.sidecar, prep.issuer_idx, prep.valid,
                    prep.host_data, prep.length,
                )
            else:
                pending = self.aggregator.ingest_packed_submit(
                    prep.data, prep.length, prep.issuer_idx, prep.valid,
                    host_data=prep.host_data,
                )
            dec = prep.dec
            items.append((
                "pending", pending,
                lambda pos, _d=dec: _d.data[pos, : _d.length[pos]].tobytes(),
            ))
        if prep.walker_fallback:
            fb = prep.walker_fallback
            res_fb = self.aggregator.ingest(fb)
            items.append((
                "result", res_fb, lambda pos, _o=fb: _o[pos][0],
            ))
        if prep.oversized:
            oversized = prep.oversized
            res_over = self.aggregator.ingest(oversized)
            items.append((
                "result", res_over, lambda pos, _o=oversized: _o[pos][0],
            ))
        metrics.incr_counter(
            "ct-fetch", "insertCertificate",
            value=float(int(prep.valid.sum()) + len(prep.oversized)
                        + len(prep.walker_fallback)),
        )
        return items

    def _complete_item(self, pending, der_of) -> None:
        """Stage 3 — block on one batch's device work and fold it.

        The completeBatch sample is where the pipeline's device wait
        really lives: device execution + D2H readback + the exact
        host-lane work for flagged lanes — the counterpart of the
        (async-enqueue) storeCertificate/h2dSubmit samples."""
        with metrics.measure("ct-fetch", "completeBatch"), \
                trace.span("device.readback", cat="device"):
            res = pending.complete()
        self._store_pems(res, der_of)

    def _drain_inflight(self, keep: int) -> None:
        """Complete submitted device work until at most ``keep`` batches
        remain in flight. Caller holds ``_dispatch_lock``."""
        while len(self._inflight) > keep:
            pending, der_of = self._inflight.popleft()
            self._complete_item(pending, der_of)

    def flush(self) -> None:
        with self._lock:
            batch, self._pending = self._pending, []
            raw, self._pending_raw = self._pending_raw, []
        if batch:
            self._dispatch(batch)
        if raw:
            self._dispatch_raw(raw)
        if self._overlap is not None:
            # Barrier through the scheduler: every chunk handed to it is
            # decoded, stepped, and folded before flush returns (and any
            # stage failure surfaces here).
            self._overlap.drain_all()
        # Same storeCertificate envelope as the dispatch path, so every
        # completeBatch sample is NESTED inside a storeCertificate
        # sample — the bench's budget breakdown subtracts one from the
        # other and flush-path completes must not skew it. (In overlap
        # mode completes are NOT nested — they run on the drain thread
        # — and the bench computes the budget accordingly.)
        t_lock = time.monotonic()
        with self._dispatch_lock:
            metrics.add_sample("ct-fetch", "dispatchLockWait",
                               value=time.monotonic() - t_lock)
            with metrics.measure("ct-fetch", "storeCertificate"):
                # Serial staged mode: a partial ring must dispatch at
                # the barrier (the overlap path flushed it on the
                # submit thread inside drain_all above).
                for item in self._flush_staging_items():
                    if item[0] == "pending":
                        self._inflight.append((item[1], item[2]))
                    else:
                        self._store_pems(item[1], item[2])
                self._drain_inflight(0)
                if self.verifier is not None:
                    # Barrier for the verify lane too: the partial
                    # device batch dispatches and every verdict folds.
                    self.verifier.drain()

    def close(self) -> None:
        """Flush, then stop the overlap scheduler's threads (no-op in
        serial mode). The sink remains usable for serial dispatch."""
        try:
            self.flush()
        finally:
            if self._overlap is not None:
                overlap, self._overlap = self._overlap, None
                overlap.close()

    def checkpointed_save(self, save_fn) -> None:
        """Flush pending entries, then run ``save_fn`` while holding the
        dispatch lock — so snapshots never observe a mid-step (donated)
        table. Used as the engine's pre-cursor-save hook: aggregate
        state must be durable BEFORE the log cursor advances past the
        entries it contains (the reference gets this for free because
        every Redis write is durable per entry)."""
        self.flush()
        with self._dispatch_lock:
            save_fn()

    def _dispatch(self, batch: list[tuple[bytes, bytes]]) -> None:
        # The aggregator's table state is donated between steps; concurrent
        # ingest calls would race on a deleted buffer.
        with self._dispatch_lock, metrics.measure("ct-fetch", "storeCertificate"):
            result = self.aggregator.ingest(batch)
            self._store_pems(result, lambda pos: batch[pos][0])
        metrics.incr_counter(
            "ct-fetch", "insertCertificate", value=float(len(batch))
        )

    def _store_pems(self, result, der_of) -> None:
        """Durable PEM tree + dirty markers (parity with
        filesystemdatabase.go:189-208). No-op without a backend.

        PEMs are written for first-seen certs only, but every
        non-filtered entry re-marks its expiry day dirty — the
        reference marks per Store call, known duplicates included
        (filesystemdatabase.go:141-144,204-208); here that collapses
        to once per day per dispatch."""
        if self.backend is None:
            return
        from ct_mapreduce_tpu.core.der import der_to_pem
        from ct_mapreduce_tpu.core.types import ExpDate, Serial

        reg = self.aggregator.registry
        with self._pem_lock:  # overlap drains + per-entry path may race
            dirty_days: set[str] = set()
            for pos, sb in enumerate(result.serials):
                if sb is None or result.filtered[pos]:
                    continue
                exp = ExpDate.from_unix_hour(int(result.exp_hours[pos]))
                dirty_days.add(exp.date.strftime("%Y-%m-%d"))
                if not result.was_unknown[pos]:
                    continue
                issuer = reg.issuer_at(int(result.issuer_idx[pos]))
                pair = (exp.id(), issuer.id())
                if pair not in self._allocated:
                    self.backend.allocate_exp_date_and_issuer(exp, issuer)
                    self._allocated.add(pair)
                self.backend.store_certificate_pem(
                    Serial(sb), exp, issuer, der_to_pem(der_of(pos))
                )
            for day in dirty_days:
                self.backend.mark_dirty(day)


@dataclass
class _PreparedChunk:
    """Output of the ingest pipeline's decode stage: one raw chunk
    decoded, packed, issuer-mapped, and (when full-batch-shaped) with
    its H2D transfer already submitted — everything the device submit
    stage needs, computed without any aggregator-state mutation."""

    data: object  # uint8[n, pad] rows — device array (H2D enqueued) or np
    host_data: np.ndarray  # host-resident copy for host-lane slices
    length: np.ndarray  # int32[n]
    issuer_idx: np.ndarray  # int32[n] registry indices
    valid: np.ndarray  # bool[n]
    dec: object  # the DecodedBatch (host rows for PEM der_of slicing)
    oversized: list  # [(cert_der, issuer_der)] exact-lane entries
    sidecar: object = None  # leafpack.Sidecar — pre-parsed lane active
    walker_fallback: list = field(default_factory=list)  # sidecar-
    # undecidable lanes, replayed through the device-walker path
    scts: object = None  # verify.sct.SctBatch — verify lane active
    verify_eligible: object = None  # bool[n] — decoded-OK lanes as of
    # extraction time (pre sidecar-split)


@dataclass
class _QueueItem:
    entry: DecodedEntry
    log_url: str


@dataclass
class RawBatch:
    """One get-entries response, undecoded — the raw-batch fast path
    hands whole responses to the sink, which decodes them natively
    (ct_mapreduce_tpu.native.leafpack) with no per-entry Python."""

    leaf_inputs: list[str]
    extra_datas: list[str]
    start_index: int
    log_url: str

    def __len__(self) -> int:
        return len(self.leaf_inputs)


class LogWorker:
    """Download worker for one log (ct-fetch.go:248-488).

    Resolves the resume window on construction: start = saved
    ``MaxEntry`` unless ``offset`` overrides; end = STH tree size - 1,
    clamped by ``limit`` (ct-fetch.go:288-305).
    """

    def __init__(
        self,
        client: CTLogClient,
        database,
        offset: int = 0,
        limit: int = 0,
        pre_save=None,
        state_suffix: str = "",
    ):
        self.client = client
        self.database = database
        self.pre_save = pre_save  # runs before each durable cursor write
        # Fleet stripe mode (ingest/fleet.py::partition_range): workers
        # share one log but own disjoint [offset, offset+limit) index
        # ranges, so each stripe keeps its OWN durable cursor under
        # `<short_url><state_suffix>` — a shared cursor would clobber
        # across workers — and resume takes max(stripe start, saved
        # cursor) with the stripe END fixed, so a warm restart replays
        # only the post-checkpoint tail of its own stripe.
        self.state_suffix = state_suffix
        self.sth = client.get_sth()
        self.log_state: CertificateLog = database.get_log_state(
            client.short_url + state_suffix)
        tree_end = self.sth.tree_size - 1
        if state_suffix:
            self.start_pos = max(offset, self.log_state.max_entry)
            self.end_pos = (min(offset + limit - 1, tree_end)
                            if limit > 0 else tree_end)
        else:
            if offset > 0:
                self.start_pos = offset
            else:
                self.start_pos = self.log_state.max_entry
            if limit > 0:
                self.end_pos = min(self.start_pos + limit - 1, tree_end)
            else:
                self.end_pos = tree_end
        self.position = self.start_pos
        self.last_entry_time: Optional[datetime] = None
        self._publish_lag()
        # External checkpoint trigger (fleet epoch ticks): the download
        # loop saves at the next batch boundary when set — same thread
        # as the periodic ticker saves, so no new concurrency.
        self._save_signal = threading.Event()

    def _publish_lag(self) -> None:
        """Ingest-lag gauge (round 23): entries between the cursor and
        the STH tree head for this worker's range — the raw signal the
        SLO layer (telemetry/fleetobs.py) compares against
        ``sloMaxIngestLag``. Keyed per log so multi-log runs expose the
        worst log, not a blended number."""
        lag = max(0, self.end_pos + 1 - self.position)
        metrics.set_gauge("ingest", "lag_entries", self.client.short_url,
                          value=float(lag))

    def request_save(self) -> None:
        """Ask the download loop to checkpoint (cursor + pre_save
        aggregate snapshot) at its next batch boundary."""
        self._save_signal.set()

    def save_state(self) -> None:
        """Persist the cursor (ct-fetch.go:371-392): dual-written by
        the database facade (cache + backend). ``pre_save`` (e.g. the
        aggregate snapshot) must succeed first — a cursor must never
        durably advance past entries whose aggregation isn't durable."""
        if self.pre_save is not None:
            self.pre_save()
        self.log_state.max_entry = self.position
        if self.last_entry_time is not None:
            self.log_state.last_entry_time = self.last_entry_time
        self.log_state.last_update_time = datetime.now(timezone.utc)
        with metrics.measure("LogWorker", self.client.short_url, "saveState"):
            self.database.save_log_state(self.log_state)

    def run(
        self,
        out: "queue.Queue",
        stop: threading.Event,
        save_period_s: float = 900.0,
        progress=None,
        raw_batches: bool = False,
    ) -> int:
        """Stream ``[start_pos, end_pos]`` into the queue; returns the
        number of entries enqueued. Checkpoints on a ticker and at exit
        (ct-fetch.go:360-368,472-473) — the exit save runs on error
        paths too, like the reference's deferred save (ct-fetch.go:367):
        a transport error mid-range must not discard up to a full save
        period of cursor progress (re-fetch is dedup-safe, but it is
        lost work). With ``raw_batches``, whole get-entries responses
        are enqueued undecoded for the sink's native batch decoder."""
        try:
            enqueued = self._run_loop(
                out, stop, save_period_s, progress, raw_batches
            )
        except BaseException:
            # Best-effort save on the error path: a failing save must
            # not replace the root-cause download error (the engine
            # records what propagates to it).
            try:
                self.save_state()
            except Exception:
                metrics.incr_counter(
                    "LogWorker", self.client.short_url, "saveStateError"
                )
            raise
        self.save_state()
        return enqueued

    def _run_loop(
        self, out, stop, save_period_s, progress, raw_batches
    ) -> int:
        enqueued = 0
        next_save = time.monotonic() + save_period_s
        index = self.position
        while index <= self.end_pos and not stop.is_set():
            batch = self.client.get_raw_entries(
                index, min(index + BATCH_SIZE - 1, self.end_pos)
            )
            if not batch:
                break
            if raw_batches:
                item = RawBatch(
                    leaf_inputs=[r.leaf_input for r in batch],
                    extra_datas=[r.extra_data for r in batch],
                    start_index=batch[0].index,
                    log_url=self.client.log_url,
                )
                submitted = False
                while not stop.is_set():
                    try:
                        out.put(item, timeout=0.25)
                        submitted = True
                        break
                    except queue.Full:
                        continue
                if not submitted:
                    break  # cursor stays put: batch never reached a worker
                enqueued += len(batch)
                index = batch[-1].index + 1
                self.position = index
                # Last DECODABLE timestamp — a garbage final entry must
                # not lose the good entries' timestamps (per-entry-path
                # parity: it updates per decoded entry).
                for raw in reversed(batch):
                    ts = decode_leaf_timestamp(raw.leaf_input)
                    if ts is not None:
                        self.last_entry_time = datetime.fromtimestamp(
                            ts / 1000.0, tz=timezone.utc
                        )
                        break
                self._publish_lag()
                if progress is not None:
                    progress(self.client.short_url, self.position, self.end_pos)
                if (self._save_signal.is_set()
                        or time.monotonic() >= next_save):
                    self._save_signal.clear()
                    self.save_state()
                    next_save = time.monotonic() + save_period_s
                continue
            for raw in batch:
                try:
                    with metrics.measure(
                        "LogWorker", self.client.short_url, "parseLeaf"
                    ):
                        entry = decode_json_entry(
                            raw.index,
                            {"leaf_input": raw.leaf_input,
                             "extra_data": raw.extra_data},
                        )
                except LeafDecodeError:
                    metrics.incr_counter(
                        "LogWorker", self.client.short_url, "parseLeafError"
                    )
                    # Tolerated skip IS durable: the cursor moves past the
                    # bad entry so restarts don't re-fetch it forever.
                    self.position = raw.index + 1
                    continue
                finally:
                    index = raw.index + 1
                self.last_entry_time = datetime.fromtimestamp(
                    entry.timestamp_ms / 1000.0, tz=timezone.utc
                )
                # select{signal | save | submit} (ct-fetch.go:466-480)
                submitted = False
                while not stop.is_set():
                    try:
                        with metrics.measure(
                            "LogWorker", self.client.short_url, "submitToChannel"
                        ):
                            out.put(_QueueItem(entry, self.client.log_url),
                                    timeout=0.25)
                        enqueued += 1
                        submitted = True
                        break
                    except queue.Full:
                        continue
                if not submitted:
                    # Stopped while the queue was full: do NOT advance the
                    # cursor past an entry that never reached a worker —
                    # resume must re-fetch it.
                    break
                self.position = raw.index + 1
                self._publish_lag()
                if progress is not None:
                    progress(self.client.short_url, self.position, self.end_pos)
                if (self._save_signal.is_set()
                        or time.monotonic() >= next_save):
                    self._save_signal.clear()
                    self.save_state()
                    next_save = time.monotonic() + save_period_s
                if stop.is_set():
                    break
        return enqueued


class _AccountingQueue:
    """Facade over the shared entry queue that bumps the engine's
    per-log outstanding watermark on each successful put (the blocking
    semantics are the inner queue's own)."""

    def __init__(self, inner: "queue.Queue", on_put):
        self._inner = inner
        self._on_put = on_put

    def put(self, item, timeout=None) -> None:
        self._inner.put(item, timeout=timeout)
        self._on_put(item)


class LogSyncEngine:
    """Queue + worker-pool runtime (ct-fetch.go:83-178).

    ``start_store_threads`` spawns the consumers; ``sync_log`` spawns
    one downloader thread per URL; ``stop`` + ``join`` replicate the
    WaitGroup shutdown ordering of main() (ct-fetch.go:610-620).
    """

    def __init__(
        self,
        sink: EntrySink,
        database,
        num_threads: int = 1,
        queue_capacity: int = ENTRY_QUEUE_CAPACITY,
        offset: int = 0,
        limit: int = 0,
        save_period_s: float = 900.0,
        checkpoint_hook=None,
        raw_batches: bool = False,
    ):
        self.sink = sink
        self.database = database
        # Runs before each durable cursor write (after the queue drains):
        # in TPU mode this snapshots the device aggregates so the cursor
        # never outruns durable aggregate state.
        self.checkpoint_hook = checkpoint_hook
        self.num_threads = num_threads
        self.offset = offset
        self.limit = limit
        self.save_period_s = save_period_s
        self.raw_batches = raw_batches
        if raw_batches:
            # Queue items are whole get-entries responses (≤ BATCH_SIZE
            # entries each); keep the same total-entry bound.
            queue_capacity = max(2, queue_capacity // BATCH_SIZE)
        self.entry_queue: "queue.Queue" = queue.Queue(maxsize=queue_capacity)
        self.stop_event = threading.Event()
        self._store_threads: list[threading.Thread] = []
        self._download_threads: list[threading.Thread] = []
        self._last_update_lock = threading.Lock()
        self._last_updates: dict[str, datetime] = {}
        self._progress: dict[str, tuple[int, int]] = {}
        self.errors: list[str] = []
        # Per-log count of entries enqueued but not yet through the sink.
        # A durable cursor save for log L only needs L's own entries
        # stored — waiting on the whole shared queue (entry_queue.join())
        # would let other logs' downloaders starve the save indefinitely.
        self._outstanding: dict[str, int] = {}
        self._outstanding_cond = threading.Condition()
        # Live LogWorkers (fleet checkpoint fan-out): registered for
        # the duration of their download, so an external checkpoint
        # tick can ask each to save at its next batch boundary.
        self._active_workers: list[LogWorker] = []
        self._active_lock = threading.Lock()

    # -- health surface (ct-fetch.go:567-597) ---------------------------
    def last_updates(self) -> dict[str, datetime]:
        with self._last_update_lock:
            return dict(self._last_updates)

    def progress(self) -> dict[str, tuple[int, int]]:
        with self._last_update_lock:
            return dict(self._progress)

    def _note_progress(self, short_url: str, pos: int, end: int) -> None:
        with self._last_update_lock:
            self._last_updates[short_url] = datetime.now(timezone.utc)
            self._progress[short_url] = (pos, end)

    # -- consumers ------------------------------------------------------
    def _store_worker(self) -> None:
        while True:
            item = self.entry_queue.get()
            try:
                if item is None:
                    return
                try:
                    if isinstance(item, RawBatch):
                        self.sink.store_raw_batch(item)
                    else:
                        self.sink.store(item.entry, item.log_url)
                except Exception as err:
                    # A store failure must not kill the worker — the queue
                    # would back up and stop() would deadlock on join().
                    metrics.incr_counter("ct-fetch", "storeError")
                    where = (
                        f"{item.log_url}@{item.start_index}"
                        if isinstance(item, RawBatch)
                        else f"{item.log_url}@{item.entry.index}"
                    )
                    self.errors.append(f"store {where}: {err}")
            finally:
                self.entry_queue.task_done()
                if item is not None:
                    self._account_stored(item)

    def start_store_threads(self) -> None:
        for i in range(self.num_threads):
            t = threading.Thread(
                target=self._store_worker, name=f"store-{i}", daemon=True
            )
            t.start()
            self._store_threads.append(t)

    def _account_enqueued(self, item) -> None:
        n = len(item) if isinstance(item, RawBatch) else 1
        with self._outstanding_cond:
            self._outstanding[item.log_url] = (
                self._outstanding.get(item.log_url, 0) + n
            )

    def _account_stored(self, item) -> None:
        n = len(item) if isinstance(item, RawBatch) else 1
        with self._outstanding_cond:
            self._outstanding[item.log_url] = (
                self._outstanding.get(item.log_url, 0) - n
            )
            self._outstanding_cond.notify_all()

    def _pre_cursor_save(self, log_url: str) -> None:
        """Make everything log ``log_url``'s cursor covers durable:
        wait until every entry *this log* enqueued has passed through
        the sink (a per-log watermark — the downloader is the one
        waiting, so its count only drains; other logs keep flowing),
        then run the checkpoint hook to flush + snapshot."""
        with self._outstanding_cond:
            self._outstanding_cond.wait_for(
                lambda: self._outstanding.get(log_url, 0) <= 0
            )
        if self.checkpoint_hook is not None:
            self.checkpoint_hook()

    # -- external checkpoint trigger (fleet epoch ticks) ----------------
    def checkpoint_now(self) -> None:
        """Checkpoint the run's durable state out of band: every live
        downloader saves (cursor + pre_save aggregate snapshot) at its
        next batch boundary; with no downloads in flight the aggregate
        snapshot hook runs directly, so idle workers still persist at
        the fleet's cadence."""
        with self._active_lock:
            workers = list(self._active_workers)
        for worker in workers:
            worker.request_save()
        if not workers and self.checkpoint_hook is not None:
            self.checkpoint_hook()

    # -- producers ------------------------------------------------------
    def sync_log(self, log_url: str, transport=None,
                 offset: Optional[int] = None, limit: Optional[int] = None,
                 state_suffix: str = "") -> threading.Thread:
        """Start one downloader. ``offset``/``limit`` override the
        engine-wide window (fleet entry-range stripes of a single log
        pass their own); ``state_suffix`` keys the stripe's durable
        cursor (see :class:`LogWorker`)."""
        eff_offset = self.offset if offset is None else offset
        eff_limit = self.limit if limit is None else limit

        def run() -> None:
            worker = None
            try:
                client = CTLogClient(log_url, transport=transport)
                worker = LogWorker(
                    client, self.database,
                    offset=eff_offset, limit=eff_limit,
                    # Items carry the client's normalized URL, so the
                    # watermark key must match it.
                    pre_save=lambda: self._pre_cursor_save(client.log_url),
                    state_suffix=state_suffix,
                )
                with self._active_lock:
                    self._active_workers.append(worker)
                self._note_progress(client.short_url, worker.position, worker.end_pos)
                worker.run(
                    _AccountingQueue(self.entry_queue, self._account_enqueued),
                    self.stop_event,
                    save_period_s=self.save_period_s,
                    progress=self._note_progress,
                    raw_batches=self.raw_batches,
                )
            except Exception as err:  # log-level failures never kill the run
                metrics.incr_counter("ct-fetch", "syncLogError")
                self.errors.append(f"{log_url}: {err}")
            finally:
                if worker is not None:
                    with self._active_lock:
                        with contextlib.suppress(ValueError):
                            self._active_workers.remove(worker)

        t = threading.Thread(target=run, name=f"sync-{log_url}", daemon=True)
        t.start()
        self._download_threads.append(t)
        return t

    # -- lifecycle ------------------------------------------------------
    def wait_for_downloads(self, timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        for t in self._download_threads:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            t.join(remaining)
        # Drop finished threads so runForever rounds don't accumulate
        # (and re-join) an ever-growing history.
        self._download_threads = [t for t in self._download_threads if t.is_alive()]

    def stop(self) -> None:
        """Drain and terminate the store workers (ct-fetch.go:167-171)."""
        self.entry_queue.join()
        for _ in self._store_threads:
            self.entry_queue.put(None)
        for t in self._store_threads:
            t.join()
        self._store_threads.clear()
        self.sink.flush()

    def signal_stop(self) -> None:
        self.stop_event.set()

    def cleanup(self) -> None:
        self.database.cleanup()


def polling_delay(mean_s: float, std_dev_pct: float) -> float:
    """runForever inter-poll sleep: normal around the mean, clamped
    positive (the reference draws from a normal distribution with the
    configured mean/stddev percentage)."""
    return max(1.0, random.gauss(mean_s, mean_s * std_dev_pct / 100.0))
