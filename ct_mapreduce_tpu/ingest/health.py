"""The /health HTTP endpoint.

Reference semantics (/root/reference/cmd/ct-fetch/ct-fetch.go:567-608):
503 before the first per-log update arrives; 500 when any log's last
update is older than 2 × pollingDelayMean ("stalled"); 200 otherwise,
with a JSON body of per-log ages.
"""

from __future__ import annotations

import json
import threading
from datetime import datetime, timezone
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class HealthServer:
    def __init__(self, engine, polling_delay_mean_s: float, addr: str = ":8080"):
        self.engine = engine
        self.stall_after_s = 2.0 * polling_delay_mean_s
        host, _, port = addr.rpartition(":")
        self.host = host or "0.0.0.0"
        self.port = int(port)
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def status(self) -> tuple[int, dict]:
        updates = self.engine.last_updates()
        if not updates:
            return 503, {"status": "no updates yet"}
        now = datetime.now(timezone.utc)
        ages = {
            url: (now - ts).total_seconds() for url, ts in updates.items()
        }
        stalled = {u: a for u, a in ages.items() if a > self.stall_after_s}
        if stalled:
            return 500, {"status": "stalled", "ages_s": ages, "stalled": list(stalled)}
        return 200, {"status": "ok", "ages_s": ages}

    def start(self) -> None:
        health = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802  (http.server API)
                if self.path.rstrip("/") not in ("", "/health"):
                    self.send_error(404)
                    return
                code, body = health.status()
                payload = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *args):  # silence per-request stderr spam
                pass

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._server.server_address[1]  # resolve port 0
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="health", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
