"""Prometheus ``/metrics`` + ``/healthz`` over a stdlib HTTP server.

The reference exposes one ``/health`` endpoint and pushes metrics to
StatsD (engine.go:50-86); a production deployment of THIS engine wants
pull-based scraping: ``metricsPort`` starts a background
``ThreadingHTTPServer`` rendering the primary
:class:`~ct_mapreduce_tpu.telemetry.metrics.InMemSink` snapshot in
Prometheus text exposition format (version 0.0.4) —

- counters → ``counter``
- gauges → ``gauge``
- timing samples → ``summary`` with p50/p95/p99 quantiles plus
  ``_sum``/``_count``

— and ``/healthz`` as JSON: engine stage, last-progress timestamp, and
the overlap pipeline's bounded-queue depths, the three numbers that
distinguish "healthy", "decode-starved", and "wedged" at a glance.

No third-party client library: names are sanitized to the Prometheus
grammar (``[a-zA-Z_:][a-zA-Z0-9_:]*``) and rendering is plain string
assembly, asserted valid by the parser in tests/test_promhttp.py.
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from ct_mapreduce_tpu.telemetry import metrics as _metrics

_INVALID = re.compile(r"[^a-zA-Z0-9_:]")


def metric_name(key: str) -> str:
    """Dotted metric key → valid Prometheus metric name."""
    name = _INVALID.sub("_", key)
    if not name or name[0].isdigit():
        name = "_" + name
    return name


def _fmt(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(snap: dict) -> str:
    """Render an ``InMemSink.snapshot()`` dict as text exposition."""
    lines: list[str] = []
    for key, val in sorted(snap.get("counters", {}).items()):
        name = metric_name(key)
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name} {_fmt(val)}")
    for key, val in sorted(snap.get("gauges", {}).items()):
        name = metric_name(key)
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {_fmt(val)}")
    for key, s in sorted(snap.get("samples", {}).items()):
        name = metric_name(key)
        lines.append(f"# TYPE {name} summary")
        for q, field in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
            if field in s:
                lines.append(f'{name}{{quantile="{q}"}} {_fmt(s[field])}')
        lines.append(f"{name}_sum {_fmt(s['sum'])}")
        lines.append(f"{name}_count {_fmt(s['count'])}")
    return "\n".join(lines) + "\n"


class MetricsServer:
    """Background ``/metrics`` + ``/healthz`` server (``metricsPort``).

    ``sink`` defaults to the global primary sink (always
    snapshot-capable — see ``metrics.set_sink``); ``health`` is an
    optional callable returning the ``/healthz`` JSON dict — a
    ``"healthy": False`` entry turns the response into a 503, anything
    else (including no provider) is 200. Port 0 binds an ephemeral
    port, resolved on :meth:`start` (tests use this).

    Round 23 fleet fan-in: ``fleet_metrics`` (callable returning a
    full text exposition — telemetry/fleetobs.render_fleet_metrics
    over the coordinator fabric's obs payloads) adds
    ``GET /metrics/fleet``; ``fleet_health`` (callable returning the
    fleetobs.fleet_health rollup dict) adds ``GET /healthz/fleet``
    with the same ``healthy: False`` → 503 contract. Both 404 when
    their provider is absent — a solo worker's surface is unchanged."""

    def __init__(self, port: int, host: str = "0.0.0.0", sink=None,
                 health: Optional[Callable[[], dict]] = None,
                 fleet_metrics: Optional[Callable[[], str]] = None,
                 fleet_health: Optional[Callable[[], dict]] = None):
        self.host = host
        self.port = int(port)
        self._sink = sink
        self._health = health
        self._fleet_metrics = fleet_metrics
        self._fleet_health = fleet_health
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def _snapshot(self) -> dict:
        sink = self._sink if self._sink is not None else _metrics.get_sink()
        snap = getattr(sink, "snapshot", None)
        return snap() if snap is not None else {}

    def healthz(self) -> tuple[int, dict]:
        body: dict = {"time": time.time()}
        if self._health is not None:
            try:
                body.update(self._health())
            except Exception as err:  # health probe must answer, not 500
                return 503, {"healthy": False,
                             "error": f"{type(err).__name__}: {err}"}
        code = 503 if body.get("healthy") is False else 200
        body.setdefault("healthy", code == 200)
        return code, body

    def fleet_healthz(self) -> tuple[int, dict]:
        """The ``/healthz/fleet`` rollup with the same 503 contract as
        the per-process probe — the body always renders (a load
        balancer acts on the code, an operator reads the JSON)."""
        try:
            body = dict(self._fleet_health())
        except Exception as err:  # the rollup must answer, not 500
            return 503, {"healthy": False,
                         "error": f"{type(err).__name__}: {err}"}
        code = 503 if body.get("healthy") is False else 200
        body.setdefault("healthy", code == 200)
        body.setdefault("time", time.time())
        return code, body

    def start(self) -> "MetricsServer":
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                if path == "/metrics":
                    payload = render_prometheus(server._snapshot()).encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                    code = 200
                elif path == "/healthz":
                    code, body = server.healthz()
                    payload = json.dumps(body).encode()
                    ctype = "application/json"
                elif (path == "/metrics/fleet"
                        and server._fleet_metrics is not None):
                    try:
                        payload = server._fleet_metrics().encode()
                        code = 200
                    except Exception as err:  # scrape must answer
                        payload = (f"# fleet fan-in failed: "
                                   f"{type(err).__name__}: {err}\n").encode()
                        code = 503
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif (path == "/healthz/fleet"
                        and server._fleet_health is not None):
                    code, body = server.fleet_healthz()
                    payload = json.dumps(body).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *args):  # no per-scrape stderr spam
                pass

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._server.server_address[1]  # resolve port 0
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="promhttp", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
