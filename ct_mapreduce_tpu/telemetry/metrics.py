"""Metrics: a global-sink API in the style the reference emits through
(armon/go-metrics — counters, gauges, timing samples with dotted key
paths), with an in-memory sink periodically dumped to stderr and an
optional StatsD UDP sink.

Reference: /root/reference/telemetry/telemetry.go (MetricsDumper on a
ticker, :24-87) and /root/reference/engine/engine.go:50-86 (StatsD when
configured, else in-mem + dumper). Metric names are preserved so
dashboards keyed on the reference's names keep working; the headline
gauge for the TPU path is `entries_per_sec_per_chip`.
"""

from __future__ import annotations

import socket
import sys
import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Optional


class InMemSink:
    def __init__(self):
        self._lock = threading.Lock()
        self.counters: dict[str, float] = defaultdict(float)
        self.gauges: dict[str, float] = {}
        self.samples: dict[str, list[float]] = defaultdict(list)

    def incr_counter(self, key: str, value: float) -> None:
        with self._lock:
            self.counters[key] += value

    def set_gauge(self, key: str, value: float) -> None:
        with self._lock:
            self.gauges[key] = value

    def add_sample(self, key: str, value: float) -> None:
        with self._lock:
            samples = self.samples[key]
            samples.append(value)
            if len(samples) > 4096:  # bound memory on hot paths
                del samples[: len(samples) - 4096]

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "samples": {
                    k: {
                        "count": len(v),
                        "sum": sum(v),
                        "min": min(v),
                        "max": max(v),
                        "mean": sum(v) / len(v),
                    }
                    for k, v in self.samples.items()
                    if v
                },
            }

    def reset(self) -> None:
        with self._lock:
            self.counters.clear()
            self.gauges.clear()
            self.samples.clear()


class StatsdSink:
    """Minimal StatsD UDP emitter (engine.go:55-63 equivalent)."""

    def __init__(self, host: str, port: int, prefix: str = ""):
        self.addr = (host, port)
        self.prefix = prefix
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def _send(self, payload: str) -> None:
        try:
            self._sock.sendto(payload.encode("ascii"), self.addr)
        except OSError:
            pass  # metrics must never take down the pipeline

    def incr_counter(self, key: str, value: float) -> None:
        self._send(f"{self.prefix}{key}:{value}|c")

    def set_gauge(self, key: str, value: float) -> None:
        self._send(f"{self.prefix}{key}:{value}|g")

    def add_sample(self, key: str, value: float) -> None:
        self._send(f"{self.prefix}{key}:{value * 1000:.3f}|ms")


# -- global sink (go-metrics style) -------------------------------------

_sink: InMemSink | StatsdSink = InMemSink()
_fanout: list = []


def set_sink(sink, *extra) -> None:
    global _sink, _fanout
    _sink = sink
    _fanout = list(extra)


def get_sink():
    return _sink


def _key(parts: tuple[str, ...]) -> str:
    return ".".join(parts)


def incr_counter(*parts: str, value: float = 1.0) -> None:
    _sink.incr_counter(_key(parts), value)
    for s in _fanout:
        s.incr_counter(_key(parts), value)


def set_gauge(*parts: str, value: float) -> None:
    _sink.set_gauge(_key(parts), value)
    for s in _fanout:
        s.set_gauge(_key(parts), value)


def add_sample(*parts: str, value: float) -> None:
    _sink.add_sample(_key(parts), value)
    for s in _fanout:
        s.add_sample(_key(parts), value)


@contextmanager
def measure(*parts: str):
    """MeasureSince equivalent: time a block into a sample metric."""
    start = time.perf_counter()
    try:
        yield
    finally:
        add_sample(*parts, value=time.perf_counter() - start)


class MetricsDumper:
    """Periodic dump of in-mem metrics to stderr on a background thread
    (telemetry/telemetry.go:37-87)."""

    def __init__(self, sink: InMemSink, period_s: float, out=None):
        self.sink = sink
        self.period_s = period_s
        self.out = out if out is not None else sys.stderr
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="metrics-dumper", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            self.dump()

    def dump(self) -> None:
        snap = self.sink.snapshot()
        ts = time.strftime("%Y-%m-%d %H:%M:%S")
        lines = [f"[{ts}] metrics:"]
        for k, v in sorted(snap["gauges"].items()):
            lines.append(f"  [G] {k}: {v}")
        for k, v in sorted(snap["counters"].items()):
            lines.append(f"  [C] {k}: {v}")
        for k, s in sorted(snap["samples"].items()):
            lines.append(
                f"  [S] {k}: count={s['count']} mean={s['mean']:.6f}s "
                f"min={s['min']:.6f}s max={s['max']:.6f}s"
            )
        try:
            print("\n".join(lines), file=self.out, flush=True)
        except (ValueError, OSError):
            # The sink stream can already be closed when a dump races
            # interpreter (or pytest capture) teardown — losing one
            # periodic stats dump there is fine; crashing the dumper
            # thread with an unraisable exception is not.
            pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
