"""Metrics: a global-sink API in the style the reference emits through
(armon/go-metrics — counters, gauges, timing samples with dotted key
paths), with an in-memory sink periodically dumped to stderr and an
optional StatsD UDP sink.

Reference: /root/reference/telemetry/telemetry.go (MetricsDumper on a
ticker, :24-87) and /root/reference/engine/engine.go:50-86 (StatsD when
configured, else in-mem + dumper). Metric names are preserved so
dashboards keyed on the reference's names keep working; the headline
gauge for the TPU path is `entries_per_sec_per_chip`.

Every metric key must be listed in docs/METRICS.md — a tier-1 test
(tests/test_metrics_doc.py) walks the package's call sites and fails
on any undocumented key, the name-stability contract made enforceable.

Sink topology: the PRIMARY sink is always snapshot-capable (an
:class:`InMemSink`) so ``MetricsDumper``, the Prometheus ``/metrics``
endpoint, and the flight recorder work in every configuration;
non-snapshot emitters (StatsD) ride as fanout sinks. ``set_sink`` with
a snapshot-less sink therefore installs a fresh ``InMemSink`` as
primary and demotes the argument to fanout.
"""

from __future__ import annotations

import atexit
import math
import socket
import sys
import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Optional


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile over an ascending list (non-empty)."""
    rank = max(1, math.ceil(q * len(sorted_vals)))
    return sorted_vals[rank - 1]


class InMemSink:
    SAMPLE_RING = 4096  # per-key sample bound on hot paths

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: dict[str, float] = defaultdict(float)
        self.gauges: dict[str, float] = {}
        self.samples: dict[str, list[float]] = defaultdict(list)

    def incr_counter(self, key: str, value: float) -> None:
        with self._lock:
            self.counters[key] += value

    def set_gauge(self, key: str, value: float) -> None:
        with self._lock:
            self.gauges[key] = value

    def add_sample(self, key: str, value: float) -> None:
        with self._lock:
            samples = self.samples[key]
            samples.append(value)
            if len(samples) > self.SAMPLE_RING:
                del samples[: len(samples) - self.SAMPLE_RING]

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "samples": {},
            }
            for k, v in self.samples.items():
                if not v:
                    continue
                sv = sorted(v)
                out["samples"][k] = {
                    "count": len(v),
                    "sum": sum(v),
                    "min": sv[0],
                    "max": sv[-1],
                    "mean": sum(v) / len(v),
                    # The tail is the number that matters for lock
                    # waits and per-entry decode cost; the mean hides
                    # it (ISSUE 4 satellite).
                    "p50": _percentile(sv, 0.50),
                    "p95": _percentile(sv, 0.95),
                    "p99": _percentile(sv, 0.99),
                }
            return out

    def reset(self) -> None:
        with self._lock:
            self.counters.clear()
            self.gauges.clear()
            self.samples.clear()


class StatsdSink:
    """Minimal StatsD UDP emitter (engine.go:55-63 equivalent)."""

    def __init__(self, host: str, port: int, prefix: str = ""):
        self.addr = (host, port)
        self.prefix = prefix
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._closed = False

    def _send(self, payload: str) -> None:
        if self._closed:
            return
        try:
            self._sock.sendto(payload.encode("ascii"), self.addr)
        except OSError:
            pass  # metrics must never take down the pipeline

    def incr_counter(self, key: str, value: float) -> None:
        self._send(f"{self.prefix}{key}:{value}|c")

    def set_gauge(self, key: str, value: float) -> None:
        self._send(f"{self.prefix}{key}:{value}|g")

    def add_sample(self, key: str, value: float) -> None:
        self._send(f"{self.prefix}{key}:{value * 1000:.3f}|ms")

    def close(self) -> None:
        """Release the UDP socket; emits become no-ops. Called when
        the sink is replaced via ``set_sink`` and at interpreter
        exit."""
        if not self._closed:
            self._closed = True
            try:
                self._sock.close()
            except OSError:
                pass


# -- global sink (go-metrics style) -------------------------------------

_sink: InMemSink = InMemSink()
_fanout: list = []


def set_sink(sink, *extra) -> None:
    """Install the global sink. Snapshot-capable sinks become the
    primary; snapshot-less ones (StatsD) are demoted to fanout behind
    a fresh ``InMemSink`` so ``get_sink().snapshot()`` always works.
    Replaced sinks that own resources (``close()``) are closed —
    except ones that remain installed (the save/restore pattern swaps
    InMemSinks, which own nothing)."""
    global _sink, _fanout
    old = [_sink, *_fanout]
    if hasattr(sink, "snapshot"):
        _sink = sink
        _fanout = list(extra)
    else:
        _sink = InMemSink()
        _fanout = [sink, *extra]
    current = [_sink, *_fanout]
    for s in old:
        if s not in current and hasattr(s, "close"):
            try:
                s.close()
            except Exception:
                pass


def get_sink() -> InMemSink:
    """The primary (always snapshot-capable) sink."""
    return _sink


def get_fanout() -> list:
    return list(_fanout)


@atexit.register
def _close_sinks_at_exit() -> None:
    for s in (_sink, *_fanout):
        if hasattr(s, "close"):
            try:
                s.close()
            except Exception:
                pass


def _key(parts: tuple[str, ...]) -> str:
    return ".".join(parts)


def incr_counter(*parts: str, value: float = 1.0) -> None:
    _sink.incr_counter(_key(parts), value)
    for s in _fanout:
        s.incr_counter(_key(parts), value)


def set_gauge(*parts: str, value: float) -> None:
    _sink.set_gauge(_key(parts), value)
    for s in _fanout:
        s.set_gauge(_key(parts), value)


def add_sample(*parts: str, value: float) -> None:
    _sink.add_sample(_key(parts), value)
    for s in _fanout:
        s.add_sample(_key(parts), value)


@contextmanager
def measure(*parts: str):
    """MeasureSince equivalent: time a block into a sample metric."""
    start = time.perf_counter()
    try:
        yield
    finally:
        add_sample(*parts, value=time.perf_counter() - start)


class MetricsDumper:
    """Periodic dump of in-mem metrics to stderr on a background thread
    (telemetry/telemetry.go:37-87). ``on_snapshot`` (if given) receives
    every dumped snapshot — the flight recorder's feed."""

    def __init__(self, sink: InMemSink, period_s: float, out=None,
                 on_snapshot=None):
        self.sink = sink
        self.period_s = period_s
        self.out = out if out is not None else sys.stderr
        self.on_snapshot = on_snapshot
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="metrics-dumper", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            self.dump()

    def dump(self) -> None:
        snap = self.sink.snapshot()
        if self.on_snapshot is not None:
            try:
                self.on_snapshot(snap)
            except Exception:
                pass  # a recorder failure must not kill the dumper
        ts = time.strftime("%Y-%m-%d %H:%M:%S")
        lines = [f"[{ts}] metrics:"]
        for k, v in sorted(snap["gauges"].items()):
            lines.append(f"  [G] {k}: {v}")
        for k, v in sorted(snap["counters"].items()):
            lines.append(f"  [C] {k}: {v}")
        for k, s in sorted(snap["samples"].items()):
            lines.append(
                f"  [S] {k}: count={s['count']} mean={s['mean']:.6f}s "
                f"p50={s['p50']:.6f}s p95={s['p95']:.6f}s "
                f"p99={s['p99']:.6f}s min={s['min']:.6f}s "
                f"max={s['max']:.6f}s"
            )
        try:
            print("\n".join(lines), file=self.out, flush=True)
        except (ValueError, OSError):
            # The sink stream can already be closed when a dump races
            # interpreter (or pytest capture) teardown — losing one
            # periodic stats dump there is fine; crashing the dumper
            # thread with an unraisable exception is not.
            pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
