"""Fleet-wide observability plane (round 23).

PR 4's telemetry (span ring, ``/metrics``, ``/healthz``) is strictly
per-process: a W-worker fleet answers "is the fleet healthy, where is
the ingest lag" only by ssh-ing into every worker. This module builds
the fleet-scoped layer out of pieces that already exist — the
coordinator fabric's TTL'd value keys, :class:`InMemSink` snapshots,
and the tracer's process attrs:

- **obs payloads**: each worker's FleetService heartbeat publishes a
  compact JSON snapshot (:func:`build_obs_payload`) — (wall, monotonic)
  clock pair, fleet stats, and the full metrics snapshot — through
  ``FleetCoordinator.publish_obs``. The payload TTL equals the
  liveness timeout, so a SIGSTOP'd worker's numbers age out on the
  same clock that marks it dead.
- **metrics fan-in**: :func:`render_fleet_metrics` renders every
  worker's payload as one Prometheus exposition — per-worker
  ``{worker="N"}`` series plus unlabeled fleet-summed counter lines,
  parity-pinned: within one response body the fleet total is exactly
  the sum of the worker-labeled lines (asserted by the smoke gate).
- **health rollup**: :func:`fleet_health` answers ``/healthz/fleet`` —
  per-worker liveness/role/heartbeat age, leader-epoch skew,
  checkpoint chain depth, and any worker's SLO degradation; a missing
  or stale worker flips the rollup unhealthy (HTTP 503).
- **SLO rules**: :func:`evaluate_slos` turns raw signals (the
  ``ingest.lag_entries.*`` gauges, checkpoint age, filter publish
  epoch lag, span-derived serve p99) into ``slo.*`` gauges with
  thresholds from the ``obs`` knob section; a breach flips the
  per-process ``/healthz`` to degraded and is visible in the rollup.
- **clock skew**: the pure correction math behind
  ``traceview --merge`` (:func:`clock_offset`,
  :func:`corrected_epoch_us`, :func:`merge_traces`) — workers publish
  (wall, monotonic) pairs through the fabric; the merger rebases every
  per-process Chrome trace onto one corrected wall timeline.

Thresholds default to 0 = disabled, so behavior is unchanged until a
deployment opts in (``sloMax*`` directives / ``CTMR_SLO_*`` envs /
platform profile ``knobs.obs``).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass
from typing import Iterable, Optional

from ct_mapreduce_tpu.config import profile as platprofile
from ct_mapreduce_tpu.telemetry import metrics

OBS_VERSION = 1

# The knob section (platformProfile `knobs.obs`): fan-in on/off plus
# the SLO thresholds. All thresholds default to "disabled" (0) so the
# rule layer is opt-in; fleetMetrics defaults on because publishing
# rides a heartbeat that is already being sent.
_OBS_KNOBS = (
    platprofile.Knob("fleetMetrics", "CTMR_FLEET_METRICS", True,
                     parse=platprofile.parse_bool_strict,
                     env_is_set=platprofile.any_set, post=bool),
    platprofile.Knob("sloMaxIngestLag", "CTMR_SLO_MAX_INGEST_LAG", 0,
                     parse=int, is_set=platprofile.pos_int, post=int),
    platprofile.Knob("sloMaxCheckpointAge", "CTMR_SLO_MAX_CKPT_AGE_S", 0.0,
                     parse=float, is_set=platprofile.pos_float, post=float),
    platprofile.Knob("sloMaxFilterLag", "CTMR_SLO_MAX_FILTER_LAG", 0,
                     parse=int, is_set=platprofile.pos_int, post=int),
    platprofile.Knob("sloMaxServeP99Ms", "CTMR_SLO_MAX_SERVE_P99_MS", 0.0,
                     parse=float, is_set=platprofile.pos_float, post=float),
)


@dataclass(frozen=True)
class ObsKnobs:
    fleet_metrics: bool
    max_ingest_lag: int
    max_ckpt_age_s: float
    max_filter_lag: int
    max_serve_p99_ms: float

    def any_slo(self) -> bool:
        return bool(self.max_ingest_lag or self.max_ckpt_age_s
                    or self.max_filter_lag or self.max_serve_p99_ms)


def resolve_obs(fleet_metrics=None, max_ingest_lag=None,
                max_ckpt_age_s=None, max_filter_lag=None,
                max_serve_p99_ms=None) -> ObsKnobs:
    """The ``obs`` section through the platformProfile ladder
    (explicit > CTMR_* env > profile > default)."""
    knobs = platprofile.resolve_section("obs", _OBS_KNOBS, {
        "fleetMetrics": fleet_metrics,
        "sloMaxIngestLag": max_ingest_lag,
        "sloMaxCheckpointAge": max_ckpt_age_s,
        "sloMaxFilterLag": max_filter_lag,
        "sloMaxServeP99Ms": max_serve_p99_ms,
    })
    return ObsKnobs(
        fleet_metrics=knobs["fleetMetrics"],
        max_ingest_lag=knobs["sloMaxIngestLag"],
        max_ckpt_age_s=knobs["sloMaxCheckpointAge"],
        max_filter_lag=knobs["sloMaxFilterLag"],
        max_serve_p99_ms=knobs["sloMaxServeP99Ms"],
    )


# -- clock pairs + skew correction ---------------------------------------


def clock_pair() -> dict:
    """One (wall, monotonic) sample, read back to back — the unit of
    the coordinator-fabric timestamp exchange."""
    return {"wall": time.time(), "mono": time.monotonic()}


def clock_offset(pair: dict) -> float:
    """wall = mono + offset for the process that published ``pair``.
    On one host the monotonic clock is system-wide (per boot), so two
    processes' offsets differ only by their wall-read jitter; across
    hosts the fabric exchange carries each machine's own offset."""
    return float(pair["wall"]) - float(pair["mono"])


def corrected_epoch_us(ts_us: float, mono_t0: float,
                       offset: float) -> float:
    """A trace event timestamp (µs since the tracer's perf_counter
    base, anchored at ``mono_t0`` on the monotonic clock) → absolute
    wall-epoch µs via that process's clock offset."""
    return (mono_t0 + offset) * 1e6 + float(ts_us)


def _doc_offset(doc: dict, pairs: Optional[dict]) -> float:
    """The clock offset for one exported trace doc: the fabric pair
    for its worker when one was exchanged, else the (wall_t0, mono_t0)
    pair the tracer itself sampled at startup."""
    other = doc.get("otherData", {})
    if pairs:
        attrs = other.get("process_attrs", {}) or {}
        worker = attrs.get("worker")
        if worker is not None and worker in pairs:
            return clock_offset(pairs[worker])
        if str(worker) in pairs:
            return clock_offset(pairs[str(worker)])
    return (float(other.get("wall_t0", 0.0))
            - float(other.get("mono_t0", 0.0)))


def merge_traces(docs: Iterable[dict],
                 pairs: Optional[dict] = None) -> dict:
    """Stitch per-process Chrome-trace docs into ONE timeline.

    Each doc's events are shifted onto the corrected wall clock
    (fabric ``pairs`` keyed by worker id when available, the doc's own
    startup pair otherwise), then the whole timeline is rebased so the
    earliest event sits at ts=0 — Perfetto renders one run, clock skew
    gone. Process metadata events name each track by worker/pid."""
    docs = list(docs)
    shifted: list[tuple[dict, float, dict]] = []
    t_min: Optional[float] = None
    for doc in docs:
        other = doc.get("otherData", {})
        mono_t0 = float(other.get("mono_t0",
                                  other.get("wall_t0", 0.0)))
        offset = _doc_offset(doc, pairs)
        base_us = corrected_epoch_us(0.0, mono_t0, offset)
        shifted.append((doc, base_us, other))
        for ev in doc.get("traceEvents", []):
            if "ts" in ev:
                t = base_us + float(ev["ts"])
                t_min = t if t_min is None else min(t_min, t)
    if t_min is None:
        t_min = 0.0
    events: list[dict] = []
    for doc, base_us, other in shifted:
        pid = other.get("pid", 0)
        attrs = other.get("process_attrs", {}) or {}
        worker = attrs.get("worker")
        label = (f"worker {worker} (pid {pid})"
                 if worker is not None else f"pid {pid}")
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": label}})
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            if "ts" in ev:
                ev["ts"] = base_us + float(ev["ts"]) - t_min
            events.append(ev)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "merged_from": len(docs),
            "epoch_us_at_ts0": t_min,
            "skew_corrected": bool(pairs),
        },
    }


# -- obs payloads (the fabric fan-in unit) -------------------------------


def build_obs_payload(worker_id: int, num_workers: int,
                      fleet_stats: Optional[dict] = None,
                      slo: Optional[dict] = None,
                      sink=None) -> str:
    """One worker's heartbeat-cadence snapshot as compact JSON: clock
    pair (the traceview skew exchange rides the same key), fleet
    stats, SLO state, and the full metrics snapshot."""
    s = sink if sink is not None else metrics.get_sink()
    snap_fn = getattr(s, "snapshot", None)
    doc = {
        "v": OBS_VERSION,
        "worker": int(worker_id),
        "num_workers": int(num_workers),
        "wall": time.time(),
        "mono": time.monotonic(),
        "metrics": snap_fn() if snap_fn is not None else {},
    }
    if fleet_stats:
        doc["fleet"] = fleet_stats
    if slo:
        doc["slo"] = slo
    payload = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    metrics.set_gauge("fleetobs", "payload_bytes",
                      value=float(len(payload)))
    return payload


def parse_obs_payload(raw: str) -> Optional[dict]:
    """Tolerant decode: a corrupt/foreign payload in the fabric must
    degrade to "worker not reporting", never crash the scrape."""
    try:
        doc = json.loads(raw)
    except (TypeError, ValueError):
        return None
    if not isinstance(doc, dict) or not isinstance(
            doc.get("metrics", {}), dict):
        return None
    if doc.get("v", OBS_VERSION) != OBS_VERSION:
        return None
    return doc


def collect_fleet_obs(raw_payloads: dict) -> dict:
    """``coordinator.fleet_obs()`` output → {worker_id: parsed doc},
    dropping anything unparseable."""
    out: dict = {}
    for wid, raw in sorted(raw_payloads.items()):
        doc = parse_obs_payload(raw)
        if doc is not None:
            out[int(wid)] = doc
    return out


def clock_pairs_from_obs(payloads: dict) -> dict:
    """The traceview skew exchange: worker id → (wall, mono) pair."""
    pairs = {}
    for wid, doc in payloads.items():
        if "wall" in doc and "mono" in doc:
            pairs[int(wid)] = {"wall": doc["wall"], "mono": doc["mono"]}
    return pairs


# -- metrics fan-in ------------------------------------------------------


def render_fleet_metrics(payloads: dict) -> str:
    """Every worker's snapshot as ONE Prometheus exposition.

    Counters render one ``{worker="N"}`` series per reporting worker
    plus an unlabeled fleet-summed line — the parity pin: within this
    body, ``metric == sum(metric{worker=...})`` exactly (same floats,
    summed here, no re-scrape race). Gauges and sample summaries are
    per-worker only: summing gauges across workers is meaningless.
    """
    from ct_mapreduce_tpu.telemetry.promhttp import _fmt, metric_name

    workers = sorted(payloads)
    lines: list[str] = []

    counter_keys: set = set()
    gauge_keys: set = set()
    sample_keys: set = set()
    for wid in workers:
        snap = payloads[wid].get("metrics", {})
        counter_keys.update(snap.get("counters", {}))
        gauge_keys.update(snap.get("gauges", {}))
        sample_keys.update(snap.get("samples", {}))

    for key in sorted(counter_keys):
        name = metric_name(key)
        lines.append(f"# TYPE {name} counter")
        total = 0.0
        for wid in workers:
            vals = payloads[wid].get("metrics", {}).get("counters", {})
            if key in vals:
                total += float(vals[key])
                lines.append(f'{name}{{worker="{wid}"}} {_fmt(vals[key])}')
        lines.append(f"{name} {_fmt(total)}")
    for key in sorted(gauge_keys):
        name = metric_name(key)
        lines.append(f"# TYPE {name} gauge")
        for wid in workers:
            vals = payloads[wid].get("metrics", {}).get("gauges", {})
            if key in vals:
                lines.append(f'{name}{{worker="{wid}"}} {_fmt(vals[key])}')
    for key in sorted(sample_keys):
        name = metric_name(key)
        lines.append(f"# TYPE {name} summary")
        for wid in workers:
            s = payloads[wid].get("metrics", {}).get("samples", {})
            if key not in s:
                continue
            s = s[key]
            for q, field in (("0.5", "p50"), ("0.95", "p95"),
                             ("0.99", "p99")):
                if field in s:
                    lines.append(f'{name}{{worker="{wid}",quantile="{q}"}}'
                                 f' {_fmt(s[field])}')
            lines.append(f'{name}_sum{{worker="{wid}"}} {_fmt(s["sum"])}')
            lines.append(
                f'{name}_count{{worker="{wid}"}} {_fmt(s["count"])}')
    metrics.set_gauge("fleetobs", "workers_reporting",
                      value=float(len(workers)))
    return "\n".join(lines) + "\n"


def fleet_counter_parity(body: str) -> list[str]:
    """Parity check over one rendered exposition body: every unlabeled
    counter line must equal the sum of its ``{worker=...}`` lines.
    Returns the violating metric names (empty = parity holds) — the
    smoke gate's assertion, usable against a live scrape."""
    import re

    worker_re = re.compile(r'^([a-zA-Z0-9_:]+)\{worker="\d+"\} (\S+)$')
    total_re = re.compile(r"^([a-zA-Z0-9_:]+) (\S+)$")
    counters: set = set()
    sums: dict = {}
    totals: dict = {}
    cur_type = ""
    for line in body.splitlines():
        if line.startswith("# TYPE "):
            parts = line.split()
            cur_type = parts[3] if len(parts) >= 4 else ""
            if cur_type == "counter":
                counters.add(parts[2])
            continue
        m = worker_re.match(line)
        if m and m.group(1) in counters:
            sums[m.group(1)] = sums.get(m.group(1), 0.0) + float(m.group(2))
            continue
        m = total_re.match(line)
        if m and m.group(1) in counters:
            totals[m.group(1)] = float(m.group(2))
    return sorted(
        name for name, total in totals.items()
        if abs(total - sums.get(name, 0.0)) > 1e-9 * max(1.0, abs(total)))


# -- SLO rules -----------------------------------------------------------


def serve_p99_ms(tracer=None) -> Optional[float]:
    """Span-derived serve p99: the p99 of ``serve.wait`` span
    durations currently in the trace ring (the full submit→reply wait
    each client saw), in milliseconds. None when tracing is off or no
    serve spans landed yet."""
    if tracer is None:
        from ct_mapreduce_tpu.telemetry import trace

        tracer = trace.get_tracer()
    if tracer is None:
        return None
    durs = sorted(float(ev.get("dur", 0.0))
                  for ev in tracer.events()
                  if ev.get("ph") == "X" and ev.get("name") == "serve.wait")
    if not durs:
        return None
    idx = min(len(durs) - 1, int(0.99 * (len(durs) - 1) + 0.5))
    return durs[idx] / 1000.0


def evaluate_slos(knobs: ObsKnobs, snap: Optional[dict] = None, *,
                  now: Optional[float] = None,
                  last_checkpoint_wall: float = 0.0,
                  checkpoint_period_s: float = 0.0,
                  filter_epoch_lag: Optional[int] = None,
                  p99_ms: Optional[float] = None) -> tuple[dict, list]:
    """Raw signals → (slo values, breach reasons).

    Pure given its inputs (timestamps and snapshot passed in), so the
    threshold edges unit-test exactly. Signals:

    - ingest lag: max over the ``ingest.lag_entries.*`` gauges in
      ``snap`` (cursor vs STH tree head, worst log wins)
    - checkpoint age: ``now - last_checkpoint_wall`` — only once a
      first checkpoint exists, and graded against
      ``max(sloMaxCheckpointAge, checkpoint period)`` so a threshold
      tighter than the cadence can't flap
    - filter publish epoch lag: caller-computed (checkpoint epoch vs
      the serve tier's published filter epoch)
    - serve p99: span-derived (:func:`serve_p99_ms`), milliseconds
    """
    now = time.time() if now is None else now
    values: dict = {}
    degraded: list = []

    if snap is not None:
        lags = [float(v) for k, v in snap.get("gauges", {}).items()
                if k.startswith("ingest.lag_entries.")]
        if lags:
            values["ingest_lag_entries"] = max(lags)
            if (knobs.max_ingest_lag
                    and values["ingest_lag_entries"] > knobs.max_ingest_lag):
                degraded.append(
                    f"ingest_lag {values['ingest_lag_entries']:.0f} > "
                    f"{knobs.max_ingest_lag}")

    if last_checkpoint_wall > 0:
        age = max(0.0, now - last_checkpoint_wall)
        values["checkpoint_age_s"] = age
        limit = max(knobs.max_ckpt_age_s, checkpoint_period_s)
        if knobs.max_ckpt_age_s and age > limit:
            degraded.append(f"checkpoint_age {age:.1f}s > {limit:.1f}s")

    if filter_epoch_lag is not None:
        values["filter_epoch_lag"] = float(filter_epoch_lag)
        if knobs.max_filter_lag and filter_epoch_lag > knobs.max_filter_lag:
            degraded.append(
                f"filter_epoch_lag {filter_epoch_lag} > "
                f"{knobs.max_filter_lag}")

    if p99_ms is not None:
        values["serve_p99_ms"] = float(p99_ms)
        if knobs.max_serve_p99_ms and p99_ms > knobs.max_serve_p99_ms:
            degraded.append(
                f"serve_p99 {p99_ms:.2f}ms > {knobs.max_serve_p99_ms}ms")

    return values, degraded


def publish_slo_gauges(values: dict, degraded: list) -> None:
    """Mirror one SLO evaluation into ``slo.*`` gauges so scrapes (and
    the fan-in) carry the derived signals, not just the raw ones."""
    for key, val in values.items():
        metrics.set_gauge("slo", key, value=float(val))
    metrics.set_gauge("slo", "degraded",
                      value=1.0 if degraded else 0.0)


# -- health rollup -------------------------------------------------------


def fleet_health(payloads: dict, num_workers: int,
                 liveness_timeout_s: float, *,
                 now: Optional[float] = None) -> dict:
    """The ``/healthz/fleet`` body: every worker's liveness, role,
    heartbeat age, epoch, claims, and SLO state, plus the rollup
    verdict. Degraded (``healthy: False``) when any expected worker is
    missing/stale, leader epochs disagree beyond one tick (a worker
    still observing epoch N-1 mid-propagation is normal), no leader is
    reporting, or any worker reports SLO breaches."""
    now = time.time() if now is None else now
    workers: dict = {}
    degraded: list = []
    epochs: list = []
    leaders = 0
    for wid, doc in sorted(payloads.items()):
        fleet = doc.get("fleet", {}) or {}
        age = max(0.0, now - float(doc.get("wall", 0.0)))
        entry = {
            "role": fleet.get("role", "unknown"),
            "age_s": round(age, 3),
            "epoch": fleet.get("checkpoint_epoch"),
            "claims": fleet.get("claims", []),
            "checkpoints_run": fleet.get("checkpoints_run"),
            "slo_degraded": list(doc.get("slo", {}).get("degraded", [])),
        }
        workers[str(wid)] = entry
        if entry["role"] == "leader":
            leaders += 1
        if entry["epoch"] is not None:
            epochs.append(int(entry["epoch"]))
        if age > liveness_timeout_s:
            degraded.append(f"worker {wid} stale ({age:.1f}s)")
        for reason in entry["slo_degraded"]:
            degraded.append(f"worker {wid} slo: {reason}")
    missing = sorted(set(range(num_workers)) - set(payloads))
    for wid in missing:
        degraded.append(f"worker {wid} not reporting")
    epoch_skew = (max(epochs) - min(epochs)) if epochs else 0
    if epoch_skew > 1:
        degraded.append(f"leader-epoch skew {epoch_skew}")
    if payloads and leaders == 0:
        degraded.append("no leader reporting")
    chain_depths = {
        str(wid): doc.get("metrics", {}).get("gauges", {}).get(
            "ckpt.chain_length")
        for wid, doc in payloads.items()
        if doc.get("metrics", {}).get("gauges", {}).get(
            "ckpt.chain_length") is not None
    }
    body = {
        "healthy": not degraded,
        "num_workers": num_workers,
        "workers_reporting": len(payloads),
        "missing": missing,
        "workers": workers,
        "leader_epoch_skew": epoch_skew,
        "ckpt_chain_depth": chain_depths,
        "liveness_timeout_s": liveness_timeout_s,
    }
    if degraded:
        body["degraded"] = degraded
    return body
