from ct_mapreduce_tpu.telemetry import metrics, trace  # noqa: F401
from ct_mapreduce_tpu.telemetry.metrics import (  # noqa: F401
    InMemSink,
    MetricsDumper,
    StatsdSink,
)
