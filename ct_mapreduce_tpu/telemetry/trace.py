"""Span tracing: a lock-cheap, thread-aware, ring-buffered tracer for
the ingest hot path, exporting Chrome trace-event JSON.

Design constraints (the hot path dispatches ~1M-entry chunks, so spans
are per-CHUNK, but the disabled path must still cost nothing):

- **Disabled = one global read.** ``span()`` reads one module global;
  when no tracer is installed it returns a shared no-op context
  manager — no allocation, no lock, no branch beyond the None check.
- **Enabled = GIL-atomic appends.** Events land in a
  ``collections.deque(maxlen=ring)`` whose ``append`` is atomic under
  the GIL, so concurrent stage threads (decode pool, submit, drain)
  never contend on a lock in ``__exit__``. The ring bound (default
  2^16 events, ``CTMR_TRACE_RING``) means a week-long ``runForever``
  deployment keeps the LAST window of activity instead of growing
  without limit — exactly what the flight recorder wants.
- **Chrome trace-event format.** Export is the Trace Event Format's
  JSON-object form (``{"traceEvents": [...]}``): complete spans
  (``ph="X"`` with ``ts``/``dur`` in microseconds), instant events
  (``ph="i"``), and thread-name metadata (``ph="M"``) — loadable in
  Perfetto / ``chrome://tracing`` as-is, and summarizable offline by
  ``tools/traceview.py``.
- **Optional XLA alignment.** ``jax_annotations=True`` (or
  ``CTMR_TRACE_JAX=1``) additionally enters a
  ``jax.profiler.TraceAnnotation`` per span, so when a jax profiler
  trace (``profileDir``) runs alongside, the host-side stage spans
  line up with the device timeline in the same viewer.

Enabling: the ``CTMR_TRACE=<path>`` environment variable (read at
import, so every entry point — ct-fetch, bench, tests — gets it for
free) or the ``tracePath`` config directive / an explicit
:func:`enable` call. When a path is set, the ring is exported there at
interpreter exit; callers may also :func:`export` eagerly.

Cross-process correlation (round 23): spans can carry a
``trace_id``/``parent_id`` pair. A request thread establishes the pair
with :func:`trace_context` (typically parsed from a W3C-style
``traceparent`` header minted by :func:`mint_traceparent`) and every
span recorded on that thread while the context is active is tagged.
:func:`set_process_attrs` stamps process-wide identity (fleet
``worker``, leader ``epoch``) onto every event, and exports carry a
``mono_t0`` anchor on ``time.monotonic()`` — the clock the coordinator
fabric's (wall, monotonic) pairs reference — so
``tools/traceview.py --merge`` can place per-process rings on one
skew-corrected timeline.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import deque
from typing import Optional

DEFAULT_RING = 1 << 16  # events; ~25 MB worst case, bounds long runs

# -- cross-process correlation state ------------------------------------
# Process-wide attrs (fleet worker id, leader epoch) merged into every
# recorded event; span-local args win on key collisions.
_proc_attrs: dict = {}
# Per-thread trace context: (trace_id, parent_id) or absent.
_ctx = threading.local()


def set_process_attrs(**attrs) -> None:
    """Stamp (or update) process-wide attrs onto every future event.
    ``None`` values delete the key."""
    for key, val in attrs.items():
        if val is None:
            _proc_attrs.pop(key, None)
        else:
            _proc_attrs[key] = val


def get_process_attrs() -> dict:
    return dict(_proc_attrs)


def set_trace_context(trace_id: str,
                      parent_id: Optional[str] = None) -> None:
    _ctx.ids = (trace_id, parent_id)


def clear_trace_context() -> None:
    _ctx.ids = None


def get_trace_context() -> Optional[tuple]:
    """The calling thread's (trace_id, parent_id), or None."""
    return getattr(_ctx, "ids", None)


class trace_context:
    """Context manager scoping a (trace_id, parent_id) pair to the
    calling thread; restores the previous context on exit. A falsy
    ``trace_id`` makes it a no-op (so callers can pass a parse result
    straight through)."""

    __slots__ = ("_ids", "_prev")

    def __init__(self, trace_id: Optional[str],
                 parent_id: Optional[str] = None):
        self._ids = (trace_id, parent_id) if trace_id else None

    def __enter__(self):
        self._prev = getattr(_ctx, "ids", None)
        if self._ids is not None:
            _ctx.ids = self._ids
        return self

    def __exit__(self, *exc):
        _ctx.ids = self._prev
        return False


# -- W3C-traceparent-style header helpers -------------------------------
# Wire shape: "00-<32 hex trace_id>-<16 hex span_id>-01" (version and
# sampled flag fixed; only the two ids are meaningful here).

TRACEPARENT_HEADER = "traceparent"


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def mint_traceparent() -> tuple[str, str, str]:
    """(header_value, trace_id, span_id) for a new client-side root."""
    trace_id, span_id = new_trace_id(), new_span_id()
    return f"00-{trace_id}-{span_id}-01", trace_id, span_id


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(header: Optional[str]) -> Optional[tuple]:
    """(trace_id, span_id) from a traceparent header, or None on any
    malformation — propagation must never reject a request."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, _flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(version, 16), int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id


class _NullSpan:
    """Shared no-op context manager: the disabled-tracing fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """One live span; records a complete ("X") event on exit."""

    __slots__ = ("_tracer", "_name", "_cat", "_args", "_t0", "_ann")

    def __init__(self, tracer: "SpanTracer", name: str, cat: str, args):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._args = args
        self._ann = None

    def __enter__(self):
        if self._tracer.jax_annotations:
            try:
                from jax.profiler import TraceAnnotation

                self._ann = TraceAnnotation(self._name)
                self._ann.__enter__()
            except Exception:
                self._ann = None  # tracing must never break the pipeline
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter_ns()
        if self._ann is not None:
            try:
                self._ann.__exit__(*exc)
            except Exception:
                pass
        self._tracer._complete(self._name, self._cat, self._t0, t1,
                               self._args)
        return False


class SpanTracer:
    def __init__(self, path: Optional[str] = None,
                 ring_size: int = DEFAULT_RING,
                 jax_annotations: bool = False):
        self.path = path or None
        self.ring_size = max(16, int(ring_size))
        self.jax_annotations = bool(jax_annotations)
        # deque.append is GIL-atomic: the hot path never takes a lock.
        self._events: deque = deque(maxlen=self.ring_size)
        self._t0_ns = time.perf_counter_ns()
        # Anchors recorded back to back: wall-clock (place the ring in
        # real time) and CLOCK_MONOTONIC (the clock the fleet fabric's
        # (wall, monotonic) pairs reference — the skew-correction base
        # for tools/traceview.py --merge).
        self.wall_t0 = time.time()
        self.mono_t0 = time.monotonic()
        self._pid = os.getpid()
        self._threads_lock = threading.Lock()
        self._thread_names: dict[int, str] = {}

    # -- recording -------------------------------------------------------
    def now_us(self) -> float:
        """Current timestamp on the tracer's own clock (µs since
        construction) — for callers windowing :meth:`events`."""
        return (time.perf_counter_ns() - self._t0_ns) / 1e3

    def _tid(self) -> int:
        tid = threading.get_ident()
        if tid not in self._thread_names:
            with self._threads_lock:
                self._thread_names.setdefault(
                    tid, threading.current_thread().name)
        return tid

    def _tagged_args(self, args) -> Optional[dict]:
        """Span args merged with the process attrs and the calling
        thread's trace context (span-local args win)."""
        ids = getattr(_ctx, "ids", None)
        if not _proc_attrs and ids is None:
            return dict(args) if args else None
        merged = dict(_proc_attrs)
        if ids is not None:
            merged["trace_id"] = ids[0]
            if ids[1]:
                merged["parent_id"] = ids[1]
        if args:
            merged.update(args)
        return merged

    def _complete(self, name: str, cat: str, t0_ns: int, t1_ns: int,
                  args) -> None:
        ev = {
            "name": name,
            "ph": "X",
            "ts": (t0_ns - self._t0_ns) / 1e3,
            "dur": max(t1_ns - t0_ns, 0) / 1e3,
            "pid": self._pid,
            "tid": self._tid(),
        }
        if cat:
            ev["cat"] = cat
        tagged = self._tagged_args(args)
        if tagged:
            ev["args"] = tagged
        self._events.append(ev)

    def span(self, name: str, cat: str = "", **args) -> _Span:
        return _Span(self, name, cat, args)

    def instant(self, name: str, cat: str = "", **args) -> None:
        ev = {
            "name": name,
            "ph": "i",
            "s": "t",  # thread-scoped instant
            "ts": self.now_us(),
            "pid": self._pid,
            "tid": self._tid(),
        }
        if cat:
            ev["cat"] = cat
        tagged = self._tagged_args(args)
        if tagged:
            ev["args"] = tagged
        self._events.append(ev)

    # -- reading / export ------------------------------------------------
    def events(self) -> list[dict]:
        """Ring contents plus thread-name metadata, oldest first."""
        with self._threads_lock:
            meta = [
                {"name": "thread_name", "ph": "M", "pid": self._pid,
                 "tid": tid, "args": {"name": tname}}
                for tid, tname in sorted(self._thread_names.items())
            ]
        return meta + list(self._events)

    def clear(self) -> None:
        self._events.clear()

    def export(self, path: Optional[str] = None) -> Optional[str]:
        """Write the Chrome trace JSON; returns the path (None if no
        path is known). Never raises — an unwritable trace file must
        not take down the run it describes."""
        path = path or self.path
        if not path:
            return None
        doc = {
            "traceEvents": self.events(),
            "displayTimeUnit": "ms",
            "otherData": {"wall_t0": self.wall_t0,
                          "mono_t0": self.mono_t0,
                          "pid": self._pid,
                          "process_attrs": get_process_attrs(),
                          "ring_size": self.ring_size},
        }
        try:
            with open(path, "w") as fh:
                json.dump(doc, fh)
        except OSError:
            return None
        return path


# -- module-level tracer (the hot path reads one global) ----------------

_tracer: Optional[SpanTracer] = None
_atexit_registered = False


def enabled() -> bool:
    return _tracer is not None


def get_tracer() -> Optional[SpanTracer]:
    return _tracer


def enable(path: Optional[str] = None, ring_size: Optional[int] = None,
           jax_annotations: Optional[bool] = None) -> SpanTracer:
    """Install the global tracer (idempotent: re-enabling with a path
    updates the export path of the live tracer rather than dropping
    its ring)."""
    global _tracer, _atexit_registered
    if ring_size is None:
        ring_size = int(os.environ.get("CTMR_TRACE_RING", DEFAULT_RING))
    if jax_annotations is None:
        jax_annotations = os.environ.get("CTMR_TRACE_JAX", "0") == "1"
    if _tracer is None:
        _tracer = SpanTracer(path=path, ring_size=ring_size,
                             jax_annotations=jax_annotations)
    else:
        if path:
            _tracer.path = path
        if jax_annotations:
            _tracer.jax_annotations = True
    if not _atexit_registered:
        atexit.register(_export_at_exit)
        _atexit_registered = True
    return _tracer


def disable() -> None:
    global _tracer
    _tracer = None


def _export_at_exit() -> None:
    t = _tracer
    if t is not None and t.path:
        t.export()


def span(name: str, cat: str = "", **args):
    """A span context manager; the shared no-op when tracing is off."""
    t = _tracer
    if t is None:
        return _NULL_SPAN
    return t.span(name, cat, **args)


def instant(name: str, cat: str = "", **args) -> None:
    t = _tracer
    if t is not None:
        t.instant(name, cat, **args)


def now_us() -> float:
    t = _tracer
    return t.now_us() if t is not None else 0.0


def snapshot_events() -> list[dict]:
    """Current ring contents (for the flight recorder); [] when off."""
    t = _tracer
    return t.events() if t is not None else []


def export(path: Optional[str] = None) -> Optional[str]:
    t = _tracer
    return t.export(path) if t is not None else None


# CTMR_TRACE=<path> enables tracing for any entry point at import time.
_env_path = os.environ.get("CTMR_TRACE", "")
if _env_path:
    enable(_env_path)
