"""Span tracing: a lock-cheap, thread-aware, ring-buffered tracer for
the ingest hot path, exporting Chrome trace-event JSON.

Design constraints (the hot path dispatches ~1M-entry chunks, so spans
are per-CHUNK, but the disabled path must still cost nothing):

- **Disabled = one global read.** ``span()`` reads one module global;
  when no tracer is installed it returns a shared no-op context
  manager — no allocation, no lock, no branch beyond the None check.
- **Enabled = GIL-atomic appends.** Events land in a
  ``collections.deque(maxlen=ring)`` whose ``append`` is atomic under
  the GIL, so concurrent stage threads (decode pool, submit, drain)
  never contend on a lock in ``__exit__``. The ring bound (default
  2^16 events, ``CTMR_TRACE_RING``) means a week-long ``runForever``
  deployment keeps the LAST window of activity instead of growing
  without limit — exactly what the flight recorder wants.
- **Chrome trace-event format.** Export is the Trace Event Format's
  JSON-object form (``{"traceEvents": [...]}``): complete spans
  (``ph="X"`` with ``ts``/``dur`` in microseconds), instant events
  (``ph="i"``), and thread-name metadata (``ph="M"``) — loadable in
  Perfetto / ``chrome://tracing`` as-is, and summarizable offline by
  ``tools/traceview.py``.
- **Optional XLA alignment.** ``jax_annotations=True`` (or
  ``CTMR_TRACE_JAX=1``) additionally enters a
  ``jax.profiler.TraceAnnotation`` per span, so when a jax profiler
  trace (``profileDir``) runs alongside, the host-side stage spans
  line up with the device timeline in the same viewer.

Enabling: the ``CTMR_TRACE=<path>`` environment variable (read at
import, so every entry point — ct-fetch, bench, tests — gets it for
free) or the ``tracePath`` config directive / an explicit
:func:`enable` call. When a path is set, the ring is exported there at
interpreter exit; callers may also :func:`export` eagerly.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import deque
from typing import Optional

DEFAULT_RING = 1 << 16  # events; ~25 MB worst case, bounds long runs


class _NullSpan:
    """Shared no-op context manager: the disabled-tracing fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """One live span; records a complete ("X") event on exit."""

    __slots__ = ("_tracer", "_name", "_cat", "_args", "_t0", "_ann")

    def __init__(self, tracer: "SpanTracer", name: str, cat: str, args):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._args = args
        self._ann = None

    def __enter__(self):
        if self._tracer.jax_annotations:
            try:
                from jax.profiler import TraceAnnotation

                self._ann = TraceAnnotation(self._name)
                self._ann.__enter__()
            except Exception:
                self._ann = None  # tracing must never break the pipeline
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter_ns()
        if self._ann is not None:
            try:
                self._ann.__exit__(*exc)
            except Exception:
                pass
        self._tracer._complete(self._name, self._cat, self._t0, t1,
                               self._args)
        return False


class SpanTracer:
    def __init__(self, path: Optional[str] = None,
                 ring_size: int = DEFAULT_RING,
                 jax_annotations: bool = False):
        self.path = path or None
        self.ring_size = max(16, int(ring_size))
        self.jax_annotations = bool(jax_annotations)
        # deque.append is GIL-atomic: the hot path never takes a lock.
        self._events: deque = deque(maxlen=self.ring_size)
        self._t0_ns = time.perf_counter_ns()
        # Wall-clock anchor so post-mortem readers can place the
        # monotonic timestamps in real time.
        self.wall_t0 = time.time()
        self._pid = os.getpid()
        self._threads_lock = threading.Lock()
        self._thread_names: dict[int, str] = {}

    # -- recording -------------------------------------------------------
    def now_us(self) -> float:
        """Current timestamp on the tracer's own clock (µs since
        construction) — for callers windowing :meth:`events`."""
        return (time.perf_counter_ns() - self._t0_ns) / 1e3

    def _tid(self) -> int:
        tid = threading.get_ident()
        if tid not in self._thread_names:
            with self._threads_lock:
                self._thread_names.setdefault(
                    tid, threading.current_thread().name)
        return tid

    def _complete(self, name: str, cat: str, t0_ns: int, t1_ns: int,
                  args) -> None:
        ev = {
            "name": name,
            "ph": "X",
            "ts": (t0_ns - self._t0_ns) / 1e3,
            "dur": max(t1_ns - t0_ns, 0) / 1e3,
            "pid": self._pid,
            "tid": self._tid(),
        }
        if cat:
            ev["cat"] = cat
        if args:
            ev["args"] = dict(args)
        self._events.append(ev)

    def span(self, name: str, cat: str = "", **args) -> _Span:
        return _Span(self, name, cat, args)

    def instant(self, name: str, cat: str = "", **args) -> None:
        ev = {
            "name": name,
            "ph": "i",
            "s": "t",  # thread-scoped instant
            "ts": self.now_us(),
            "pid": self._pid,
            "tid": self._tid(),
        }
        if cat:
            ev["cat"] = cat
        if args:
            ev["args"] = dict(args)
        self._events.append(ev)

    # -- reading / export ------------------------------------------------
    def events(self) -> list[dict]:
        """Ring contents plus thread-name metadata, oldest first."""
        with self._threads_lock:
            meta = [
                {"name": "thread_name", "ph": "M", "pid": self._pid,
                 "tid": tid, "args": {"name": tname}}
                for tid, tname in sorted(self._thread_names.items())
            ]
        return meta + list(self._events)

    def clear(self) -> None:
        self._events.clear()

    def export(self, path: Optional[str] = None) -> Optional[str]:
        """Write the Chrome trace JSON; returns the path (None if no
        path is known). Never raises — an unwritable trace file must
        not take down the run it describes."""
        path = path or self.path
        if not path:
            return None
        doc = {
            "traceEvents": self.events(),
            "displayTimeUnit": "ms",
            "otherData": {"wall_t0": self.wall_t0,
                          "ring_size": self.ring_size},
        }
        try:
            with open(path, "w") as fh:
                json.dump(doc, fh)
        except OSError:
            return None
        return path


# -- module-level tracer (the hot path reads one global) ----------------

_tracer: Optional[SpanTracer] = None
_atexit_registered = False


def enabled() -> bool:
    return _tracer is not None


def get_tracer() -> Optional[SpanTracer]:
    return _tracer


def enable(path: Optional[str] = None, ring_size: Optional[int] = None,
           jax_annotations: Optional[bool] = None) -> SpanTracer:
    """Install the global tracer (idempotent: re-enabling with a path
    updates the export path of the live tracer rather than dropping
    its ring)."""
    global _tracer, _atexit_registered
    if ring_size is None:
        ring_size = int(os.environ.get("CTMR_TRACE_RING", DEFAULT_RING))
    if jax_annotations is None:
        jax_annotations = os.environ.get("CTMR_TRACE_JAX", "0") == "1"
    if _tracer is None:
        _tracer = SpanTracer(path=path, ring_size=ring_size,
                             jax_annotations=jax_annotations)
    else:
        if path:
            _tracer.path = path
        if jax_annotations:
            _tracer.jax_annotations = True
    if not _atexit_registered:
        atexit.register(_export_at_exit)
        _atexit_registered = True
    return _tracer


def disable() -> None:
    global _tracer
    _tracer = None


def _export_at_exit() -> None:
    t = _tracer
    if t is not None and t.path:
        t.export()


def span(name: str, cat: str = "", **args):
    """A span context manager; the shared no-op when tracing is off."""
    t = _tracer
    if t is None:
        return _NULL_SPAN
    return t.span(name, cat, **args)


def instant(name: str, cat: str = "", **args) -> None:
    t = _tracer
    if t is not None:
        t.instant(name, cat, **args)


def now_us() -> float:
    t = _tracer
    return t.now_us() if t is not None else 0.0


def snapshot_events() -> list[dict]:
    """Current ring contents (for the flight recorder); [] when off."""
    t = _tracer
    return t.events() if t is not None else []


def export(path: Optional[str] = None) -> Optional[str]:
    t = _tracer
    return t.export(path) if t is not None else None


# CTMR_TRACE=<path> enables tracing for any entry point at import time.
_env_path = os.environ.get("CTMR_TRACE", "")
if _env_path:
    enable(_env_path)
