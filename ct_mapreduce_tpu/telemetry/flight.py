"""Crash flight recorder: post-mortem artifacts for wedged or dying
runs.

A deep-pipelined ingest engine that crashes (or gets SIGTERM'd by an
orchestrator) loses exactly the evidence needed to debug it: which
stage stalled, what the queue depths were, what the last chunks did.
The recorder keeps two rings in memory —

- the span tracer's event ring (:mod:`ct_mapreduce_tpu.telemetry.trace`),
- the last N metric snapshots (fed by ``MetricsDumper`` ticks and by
  explicit :func:`record_snapshot` calls)

— and on demand (unhandled exception, SIGTERM/SIGUSR1, or the overlap
pipeline latching a stage failure) dumps both plus a fresh metric
snapshot to a timestamped JSON file. Dumping is best-effort and
re-entrant-safe: a recorder failure must never mask the crash it is
documenting.

Install points: ``cmd/ct_fetch.py`` installs at startup and dumps from
its own signal handlers / main-loop except clause (leaving no global
hooks behind on return), ``engine.prepare_telemetry`` feeds dumper
snapshots into the ring, and ``ingest/overlap.py`` dumps when a stage
failure latches (``OverlapError``). The optional ``signals=True`` /
``excepthook=True`` hooks are for long-lived embedders without their
own handlers. Everything is a no-op until :func:`install` runs, so
library users and tests see no files unless they opt in.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from collections import deque
from typing import Optional

from ct_mapreduce_tpu.telemetry import metrics as _metrics
from ct_mapreduce_tpu.telemetry import trace as _trace

DEFAULT_SNAPSHOTS = 16


class FlightRecorder:
    def __init__(self, dir_path: str, max_snapshots: int = DEFAULT_SNAPSHOTS):
        self.dir = dir_path
        self._snaps: deque = deque(maxlen=max(1, int(max_snapshots)))
        self._lock = threading.Lock()
        self.dumps: list[str] = []  # paths written, oldest first

    def record_snapshot(self, snap: Optional[dict] = None) -> None:
        if snap is None:
            sink = _metrics.get_sink()
            take = getattr(sink, "snapshot", None)
            if take is None:
                return
            try:
                snap = take()
            except Exception:
                return
        self._snaps.append({"time": time.time(), "metrics": snap})

    def dump(self, reason: str) -> Optional[str]:
        """Write one post-mortem file; returns its path (None on any
        failure — never raises)."""
        try:
            ts = time.strftime("%Y%m%dT%H%M%S")
            path = os.path.join(
                self.dir, f"ctmr-flight-{ts}-{os.getpid()}.json")
            with self._lock:
                # A second dump in the same second (e.g. excepthook
                # after an overlap latch) appends a suffix, not a
                # clobber.
                if path in self.dumps:
                    path = os.path.join(
                        self.dir,
                        f"ctmr-flight-{ts}-{os.getpid()}-{len(self.dumps)}"
                        ".json")
                current = None
                sink = _metrics.get_sink()
                take = getattr(sink, "snapshot", None)
                if take is not None:
                    try:
                        current = take()
                    except Exception:
                        current = None
                doc = {
                    "reason": str(reason)[:2000],
                    "time": time.time(),
                    "pid": os.getpid(),
                    "trace_events": _trace.snapshot_events(),
                    "metric_snapshots": list(self._snaps),
                    "current_metrics": current,
                }
                # Registered extension sections (e.g. the lock
                # witness, analysis/witness.py): best-effort, a
                # provider failure must not lose the dump.
                for name, provider in list(_sections.items()):
                    try:
                        doc[name] = provider()
                    except Exception:
                        doc[name] = {"error": "section provider failed"}
                os.makedirs(self.dir, exist_ok=True)
                with open(path, "w") as fh:
                    json.dump(doc, fh)
                self.dumps.append(path)
            return path
        except Exception:
            return None


# -- module-level recorder (no-op until installed) ----------------------

_recorder: Optional[FlightRecorder] = None
_prev_excepthook = None
_prev_signals: dict[int, object] = {}

# Extension sections merged into every dump: name -> zero-arg provider
# returning a JSON-serializable value. The lock witness registers
# "lock_witness" here; others may follow.
_sections: dict[str, object] = {}


def register_section(name: str, provider) -> None:
    _sections[name] = provider


def unregister_section(name: str) -> None:
    _sections.pop(name, None)


def get_recorder() -> Optional[FlightRecorder]:
    return _recorder


def installed() -> bool:
    return _recorder is not None


def record_snapshot(snap: Optional[dict] = None) -> None:
    r = _recorder
    if r is not None:
        r.record_snapshot(snap)


def dump(reason: str) -> Optional[str]:
    r = _recorder
    return r.dump(reason) if r is not None else None


def _excepthook(exc_type, exc, tb):
    dump(f"unhandled exception: {exc_type.__name__}: {exc}")
    if _prev_excepthook is not None:
        _prev_excepthook(exc_type, exc, tb)


def _signal_handler(signum, frame):
    dump(f"signal {signum}")
    prev = _prev_signals.get(signum)
    if callable(prev):
        prev(signum, frame)
    elif prev == signal.SIG_DFL and signum == signal.SIGTERM:
        # Propagate the default fatal disposition after dumping.
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)
    # SIGUSR1 with no previous Python handler: dump-only, keep running
    # (the default action would kill the process we just documented).


def install(dir_path: Optional[str] = None,
            max_snapshots: int = DEFAULT_SNAPSHOTS,
            signals: bool = True,
            excepthook: bool = True) -> FlightRecorder:
    """Create the process-wide recorder (idempotent on the recorder;
    hooks install once). ``dir_path`` defaults to ``CTMR_FLIGHT_DIR``
    or the current directory."""
    global _recorder, _prev_excepthook
    if dir_path is None:
        dir_path = os.environ.get("CTMR_FLIGHT_DIR", "") or "."
    if _recorder is None:
        _recorder = FlightRecorder(dir_path, max_snapshots=max_snapshots)
    else:
        _recorder.dir = dir_path
    if excepthook and _prev_excepthook is None:
        _prev_excepthook = sys.excepthook
        sys.excepthook = _excepthook
    if signals:
        for sig in (signal.SIGTERM, signal.SIGUSR1):
            if sig in _prev_signals:
                continue
            try:
                _prev_signals[sig] = signal.getsignal(sig)
                signal.signal(sig, _signal_handler)
            except (ValueError, OSError):  # non-main thread / platform
                _prev_signals.pop(sig, None)
    return _recorder


def uninstall() -> None:
    """Remove the recorder and restore hooks (test hygiene)."""
    global _recorder, _prev_excepthook
    if _prev_excepthook is not None:
        sys.excepthook = _prev_excepthook
        _prev_excepthook = None
    for sig, prev in list(_prev_signals.items()):
        try:
            signal.signal(sig, prev)
        except (ValueError, OSError, TypeError):
            pass
    _prev_signals.clear()
    _recorder = None
