"""Upstream-style container encodings of the filter artifact: the
``mlbf`` and ``clubcard`` shapes crlite consumers already speak,
emitted alongside ``CTMRFL01`` from the same capture (ROADMAP item 4;
byte layouts specified in docs/FILTER_FORMAT.md).

- **mlbf** (``CTMRMB01``) — the rust-cascade shape: a flat binary
  stream of per-group multi-level Bloom-filter records (hash-algorithm
  tag, then per-layer ``m``/``k``/bitmap), no JSON anywhere. The
  closest relative of Mozilla's ``filter`` file in a crlite channel
  update.
- **clubcard** (``CTMRCC01``) — the partitioned shape: per group an
  *approximate* section (the layer-0 Bloom bitmap) and an *exact*
  section (the deeper exception layers), each independently offset so
  a consumer can map the approximate part and lazily fault the exact
  part — the access pattern clubcard-style consumers optimize for.

Both containers carry exactly the information of the source artifact:
``decode_container`` reconstructs a :class:`FilterArtifact` whose
every membership answer is identical to the source's (pinned by
tests/test_distrib.py). Encodings are deterministic — groups iterate
sorted, no wall-clock — so a container's bytes (and therefore its
ETag) are byte-identical on every worker of a fleet.

A container sourced from a ``CTMRFL02`` artifact writes the rev-2
magics (``CTMRMB02`` / ``CTMRCC02``): the record layout is unchanged,
but the per-group cascades were built against per-group universes, so
a consumer must know which native format a decoded artifact
re-serializes to (and which FP semantics apply to unobserved groups —
docs/FILTER_FORMAT.md). ``decode_container`` restores the matching
``fmt`` on the artifact it returns.
"""

from __future__ import annotations

import struct

import numpy as np

from ct_mapreduce_tpu.filter.artifact import (
    FORMAT_FL01,
    FORMAT_FL02,
    FilterArtifact,
    FilterGroup,
)
from ct_mapreduce_tpu.filter.cascade import BloomLayer, FilterCascade
from ct_mapreduce_tpu.telemetry.metrics import measure

MLBF_MAGIC = b"CTMRMB01"
MLBF_MAGIC2 = b"CTMRMB02"
CLUBCARD_MAGIC = b"CTMRCC01"
CLUBCARD_MAGIC2 = b"CTMRCC02"

# Source artifact format → container magic (and back). Layouts are
# identical across revs; the magic records the provenance format.
_MLBF_MAGIC_BY_FMT = {FORMAT_FL01: MLBF_MAGIC, FORMAT_FL02: MLBF_MAGIC2}
_CLUB_MAGIC_BY_FMT = {FORMAT_FL01: CLUBCARD_MAGIC,
                      FORMAT_FL02: CLUBCARD_MAGIC2}
_FMT_BY_MAGIC = {MLBF_MAGIC: FORMAT_FL01, MLBF_MAGIC2: FORMAT_FL02,
                 CLUBCARD_MAGIC: FORMAT_FL01,
                 CLUBCARD_MAGIC2: FORMAT_FL02}
# Hash-algorithm tag: 1 = the pipeline's Kirsch-Mitzenmacher double
# hash over SHA-256 fingerprint words (docs/FILTER_FORMAT.md). The
# only algorithm this build writes; readers must reject others.
HASH_ALG_KM_SHA256 = 1

CONTAINER_KINDS = ("clubcard", "mlbf")


class ContainerError(ValueError):
    """Unparseable container: wrong magic, hash tag, or truncation."""


def _pack_str(s: str) -> bytes:
    raw = s.encode()
    if len(raw) > 0xFFFF:
        raise ContainerError(f"string too long for container: {len(raw)}")
    return struct.pack("<H", len(raw)) + raw


class _Reader:
    def __init__(self, blob: bytes, pos: int = 0):
        self.blob = blob
        self.pos = pos

    def take(self, n: int) -> bytes:
        if self.pos + n > len(self.blob):
            raise ContainerError(
                f"truncated container at byte {self.pos} (+{n})")
        out = self.blob[self.pos: self.pos + n]
        self.pos += n
        return out

    def u8(self) -> int:
        return self.take(1)[0]

    def u16(self) -> int:
        return struct.unpack("<H", self.take(2))[0]

    def u32(self) -> int:
        return struct.unpack("<I", self.take(4))[0]

    def i32(self) -> int:
        return struct.unpack("<i", self.take(4))[0]

    def f64(self) -> float:
        return struct.unpack("<d", self.take(8))[0]

    def string(self) -> str:
        return self.take(self.u16()).decode()


# -- mlbf -----------------------------------------------------------------


def encode_mlbf(art: FilterArtifact) -> bytes:
    """``CTMRMB01``: magic ‖ u8 hashAlg ‖ f64 fpRate ‖ u32 nGroups ‖
    group records (sorted by (issuer, expDate)); per group: issuer ‖
    expDate (u16-length-prefixed UTF-8) ‖ i32 expHour ‖ u32 ordinal ‖
    u32 n ‖ u8 nLayers ‖ per layer u32 m ‖ u8 k ‖ u32 nWords ‖
    little-endian uint32 bitmap words."""
    with measure("distrib", "container_build_s"):
        out = bytearray(_MLBF_MAGIC_BY_FMT[art.fmt])
        out += struct.pack("<Bd", HASH_ALG_KM_SHA256, art.fp_rate)
        out += struct.pack("<I", len(art.groups))
        for (_, _), g in sorted(art.groups.items()):
            out += _pack_str(g.issuer)
            out += _pack_str(g.exp_id)
            out += struct.pack("<iII", g.exp_hour, g.ordinal, g.n)
            out += struct.pack("<B", len(g.cascade.layers))
            for layer in g.cascade.layers:
                raw = layer.words.astype("<u4").tobytes()
                out += struct.pack("<IBI", layer.m, layer.k,
                                   len(raw) // 4)
                out += raw
    return bytes(out)


def decode_mlbf(blob: bytes) -> FilterArtifact:
    if blob[:8] not in (MLBF_MAGIC, MLBF_MAGIC2):
        raise ContainerError(f"not an mlbf container ({blob[:8]!r})")
    r = _Reader(blob, 8)
    alg = r.u8()
    if alg != HASH_ALG_KM_SHA256:
        raise ContainerError(f"unknown mlbf hash algorithm {alg}")
    fp_rate = r.f64()
    groups = []
    for _ in range(r.u32()):
        issuer = r.string()
        exp_id = r.string()
        exp_hour = r.i32()
        ordinal = r.u32()
        n = r.u32()
        layers = []
        for _ in range(r.u8()):
            m = r.u32()
            k = r.u8()
            nwords = r.u32()
            words = np.frombuffer(r.take(4 * nwords),
                                  dtype="<u4").astype(np.uint32)
            layers.append(BloomLayer(m=m, k=k, words=words))
        groups.append(FilterGroup(
            issuer=issuer, exp_id=exp_id, exp_hour=exp_hour,
            ordinal=ordinal, n=n,
            cascade=FilterCascade(fp_rate=fp_rate, n_included=n,
                                  layers=layers)))
    return FilterArtifact(fp_rate=fp_rate, groups=groups,
                          fmt=_FMT_BY_MAGIC[blob[:8]])


# -- clubcard -------------------------------------------------------------


def encode_clubcard(art: FilterArtifact) -> bytes:
    """``CTMRCC01``: magic ‖ u8 hashAlg ‖ f64 fpRate ‖ u32 nGroups ‖
    directory ‖ approximate section ‖ exact section. The directory
    lists, per sorted group, its identity plus (offset, length) of its
    layer-0 bitmap in the approximate section and of its packed
    exception layers in the exact section — so a consumer can resolve
    the common case (layer-0 miss ⇒ not revoked) touching only the
    approximate bytes."""
    with measure("distrib", "container_build_s"):
        approx = bytearray()
        exact = bytearray()
        dir_out = bytearray()
        ordered = sorted(art.groups.items())
        for (_, _), g in ordered:
            layers = g.cascade.layers
            if layers:
                l0 = layers[0]
                a_off = len(approx)
                a_raw = l0.words.astype("<u4").tobytes()
                approx += a_raw
                l0_meta = struct.pack("<IBI", l0.m, l0.k,
                                      len(a_raw) // 4)
            else:
                a_off = len(approx)
                l0_meta = struct.pack("<IBI", 0, 0, 0)
            e_off = len(exact)
            exact += struct.pack("<B", max(0, len(layers) - 1))
            for layer in layers[1:]:
                raw = layer.words.astype("<u4").tobytes()
                exact += struct.pack("<IBI", layer.m, layer.k,
                                     len(raw) // 4)
                exact += raw
            dir_out += _pack_str(g.issuer)
            dir_out += _pack_str(g.exp_id)
            dir_out += struct.pack("<iII", g.exp_hour, g.ordinal, g.n)
            dir_out += l0_meta
            dir_out += struct.pack("<II", a_off, e_off)
        out = bytearray(_CLUB_MAGIC_BY_FMT[art.fmt])
        out += struct.pack("<Bd", HASH_ALG_KM_SHA256, art.fp_rate)
        out += struct.pack("<III", len(ordered), len(dir_out),
                           len(approx))
        out += dir_out + approx + exact
    return bytes(out)


def decode_clubcard(blob: bytes) -> FilterArtifact:
    if blob[:8] not in (CLUBCARD_MAGIC, CLUBCARD_MAGIC2):
        raise ContainerError(f"not a clubcard container ({blob[:8]!r})")
    r = _Reader(blob, 8)
    alg = r.u8()
    if alg != HASH_ALG_KM_SHA256:
        raise ContainerError(f"unknown clubcard hash algorithm {alg}")
    fp_rate = r.f64()
    n_groups = r.u32()
    dir_len = r.u32()
    approx_len = r.u32()
    dir_end = r.pos + dir_len
    approx_base = dir_end
    exact_base = approx_base + approx_len
    groups = []
    for _ in range(n_groups):
        issuer = r.string()
        exp_id = r.string()
        exp_hour = r.i32()
        ordinal = r.u32()
        n = r.u32()
        l0_m = r.u32()
        l0_k = r.u8()
        l0_words = r.u32()
        a_off = r.u32()
        e_off = r.u32()
        layers = []
        if l0_words:
            raw = blob[approx_base + a_off:
                       approx_base + a_off + 4 * l0_words]
            if len(raw) != 4 * l0_words:
                raise ContainerError("truncated approximate section")
            layers.append(BloomLayer(
                m=l0_m, k=l0_k,
                words=np.frombuffer(raw, dtype="<u4").astype(np.uint32)))
        er = _Reader(blob, exact_base + e_off)
        for _ in range(er.u8()):
            m = er.u32()
            k = er.u8()
            nwords = er.u32()
            words = np.frombuffer(er.take(4 * nwords),
                                  dtype="<u4").astype(np.uint32)
            layers.append(BloomLayer(m=m, k=k, words=words))
        groups.append(FilterGroup(
            issuer=issuer, exp_id=exp_id, exp_hour=exp_hour,
            ordinal=ordinal, n=n,
            cascade=FilterCascade(fp_rate=fp_rate, n_included=n,
                                  layers=layers)))
    if r.pos != dir_end:
        raise ContainerError(
            f"clubcard directory desync ({r.pos} != {dir_end})")
    return FilterArtifact(fp_rate=fp_rate, groups=groups,
                          fmt=_FMT_BY_MAGIC[blob[:8]])


# -- dispatch -------------------------------------------------------------


def encode_container(art: FilterArtifact, kind: str) -> bytes:
    if kind == "mlbf":
        return encode_mlbf(art)
    if kind == "clubcard":
        return encode_clubcard(art)
    raise ContainerError(f"unknown container kind {kind!r} "
                         f"(expected one of {CONTAINER_KINDS})")


def decode_container(blob: bytes) -> FilterArtifact:
    if blob[:8] in (MLBF_MAGIC, MLBF_MAGIC2):
        return decode_mlbf(blob)
    if blob[:8] in (CLUBCARD_MAGIC, CLUBCARD_MAGIC2):
        return decode_clubcard(blob)
    raise ContainerError(f"unknown container magic {blob[:8]!r}")
