"""Filter distribution plane (ROADMAP item 4): epoch deltas, upstream
container encodings, and the CDN-grade store the serve plane's
``/filter*`` routes publish from.

- :mod:`ct_mapreduce_tpu.distrib.delta` — the ``CTMRDL01`` stash/diff
  artifact between consecutive epochs' ``CTMRFL01`` bytes, the chain
  manifest, and the replay that is byte-identical to the full build.
- :mod:`ct_mapreduce_tpu.distrib.container` — clubcard/mlbf-style
  container encodings emitted alongside the native format.
- :mod:`ct_mapreduce_tpu.distrib.publish` — the per-worker
  :class:`FilterDistributor`: bounded epoch history, delta links with
  mandatory full-snapshot anchors, strong ETags, pre-compressed wire
  variants.

``resolve_distrib`` is the config surface: ``distribHistory`` /
``maxDeltaChain`` directives with ``CTMR_DISTRIB_HISTORY`` /
``CTMR_MAX_DELTA_CHAIN`` env equivalents, resolved through the
platformProfile ladder (``knobs.distrib``).
"""

from __future__ import annotations

from ct_mapreduce_tpu.config import profile as platprofile
from ct_mapreduce_tpu.distrib.container import (  # noqa: F401
    CONTAINER_KINDS,
    ContainerError,
    decode_container,
    encode_container,
)
from ct_mapreduce_tpu.distrib.delta import (  # noqa: F401
    DEFAULT_MAX_CHAIN,
    ChainManifest,
    DeltaError,
    apply_chain,
    apply_delta,
    compute_delta,
    split_bundle,
)
from ct_mapreduce_tpu.distrib.publish import (  # noqa: F401
    DEFAULT_HISTORY,
    FilterDistributor,
    negotiate_encoding,
    zstd_available,
)

_DISTRIB_KNOBS = (
    platprofile.Knob("distribHistory", "CTMR_DISTRIB_HISTORY",
                     DEFAULT_HISTORY, parse=int,
                     is_set=platprofile.pos_int,
                     post=lambda v: max(2, int(v))),
    platprofile.Knob("maxDeltaChain", "CTMR_MAX_DELTA_CHAIN",
                     DEFAULT_MAX_CHAIN, parse=int,
                     is_set=platprofile.pos_int,
                     post=lambda v: max(1, int(v))),
)


def resolve_distrib(history: int = 0,
                    max_chain: int = 0) -> tuple[int, int]:
    """Resolve the distribution knobs through the shared ladder:
    explicit value (config directive / kwarg) >
    ``CTMR_DISTRIB_HISTORY`` / ``CTMR_MAX_DELTA_CHAIN`` env >
    platformProfile ``knobs.distrib`` > defaults (8 epochs held; 4
    delta links before a mandatory full-snapshot anchor)."""
    r = platprofile.resolve_section("distrib", _DISTRIB_KNOBS, {
        "distribHistory": int(history or 0),
        "maxDeltaChain": int(max_chain or 0),
    })
    return r["distribHistory"], r["maxDeltaChain"]


__all__ = [
    "CONTAINER_KINDS",
    "DEFAULT_HISTORY",
    "DEFAULT_MAX_CHAIN",
    "ChainManifest",
    "ContainerError",
    "DeltaError",
    "FilterDistributor",
    "apply_chain",
    "apply_delta",
    "compute_delta",
    "decode_container",
    "encode_container",
    "negotiate_encoding",
    "resolve_distrib",
    "split_bundle",
    "zstd_available",
]
