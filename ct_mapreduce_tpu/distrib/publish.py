"""The filter-distribution store behind the serve plane's CDN tier:
published epochs, their container encodings, the delta chain, and
pre-compressed wire variants — everything ``GET /filter*`` serves.

One :class:`FilterDistributor` per worker holds a bounded history of
published full artifacts. Each ``publish(epoch, blob)``:

- computes the ``CTMRDL01`` delta link from the previous epoch
  (:mod:`ct_mapreduce_tpu.distrib.delta`) unless the chain since the
  last anchor already has ``max_chain`` links — then the new epoch is
  an **anchor** (clients older than it must full-pull; bounded replay
  work per client by construction);
- encodes the upstream containers
  (:mod:`ct_mapreduce_tpu.distrib.container`);
- records strong ETags (the SHA-256 of the exact bytes — free, the
  artifacts are deterministic) and the publish wall time for
  ``Last-Modified``.

Because artifact bytes are byte-identical on every worker of a fleet
(docs/FILTER_FORMAT.md's determinism contract), feeding each worker's
distributor the leader's merged artifact yields identical ETags,
identical deltas, and identical container bytes fleet-wide: any
replica is authoritative, and a CDN in front can collapse them.

Compression variants (gzip from the stdlib; zstd when the optional
``zstandard`` module is importable — never a hard dependency) are
built once per (artifact, encoding) and cached; ``gzip`` bytes are
deterministic too (``mtime=0``).

Publishes are ranked by source: ``"fleet"`` (the leader's merged
artifact, fanned out on epoch ticks) outranks ``"local"`` (a worker's
own build), so a follower that both emits locally and receives the
merged artifact serves the fleet bytes.
"""

from __future__ import annotations

import gzip
import hashlib
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ct_mapreduce_tpu.distrib import container as containers
from ct_mapreduce_tpu.distrib import delta as deltas
from ct_mapreduce_tpu.telemetry.metrics import (
    add_sample,
    incr_counter,
    set_gauge,
)

try:  # optional: the container image may not ship zstandard
    import zstandard as _zstd
except ImportError:  # pragma: no cover - environment-dependent
    _zstd = None

DEFAULT_HISTORY = 8

_SOURCE_RANK = {"local": 0, "fleet": 1}


def zstd_available() -> bool:
    return _zstd is not None


def compress(blob: bytes, encoding: str) -> bytes:
    if encoding == "gzip":
        # mtime=0 keeps the compressed bytes deterministic, so even the
        # encoded variants are byte-identical (and cacheable) fleet-wide.
        return gzip.compress(blob, compresslevel=6, mtime=0)
    if encoding == "zstd":
        if _zstd is None:
            raise ValueError("zstandard module not available")
        return _zstd.ZstdCompressor(level=10).compress(blob)
    raise ValueError(f"unknown encoding {encoding!r}")


def available_encodings() -> tuple[str, ...]:
    return ("zstd", "gzip") if zstd_available() else ("gzip",)


def etag_of(blob: bytes) -> str:
    """Strong ETag: quoted SHA-256 of the exact payload bytes."""
    return '"' + hashlib.sha256(blob).hexdigest() + '"'


@dataclass
class PublishedEpoch:
    epoch: int
    blob: bytes
    sha256: str
    etag: str
    created_wall: float
    containers: dict = field(default_factory=dict)  # kind -> bytes
    container_etags: dict = field(default_factory=dict)


class FilterDistributor:
    """Bounded epoch store + delta chain + compression cache. All
    methods are thread-safe (HTTP handler threads read while the
    checkpoint path publishes)."""

    def __init__(self, history: int = DEFAULT_HISTORY,
                 max_chain: int = deltas.DEFAULT_MAX_CHAIN,
                 container_kinds=containers.CONTAINER_KINDS):
        self.history = max(2, int(history))
        self.max_chain = max(1, int(max_chain))
        self.container_kinds = tuple(container_kinds)
        self._lock = threading.Lock()
        self._epochs: dict[int, PublishedEpoch] = {}
        self._links: dict[int, tuple[deltas.ChainLink, bytes]] = {}
        # from_epoch -> (link, blob)
        self._anchors: list[int] = []
        self._encoded: dict[tuple, bytes] = {}
        self._source_rank = -1

    # -- publishing ------------------------------------------------------
    def publish(self, epoch: int, blob: bytes,
                source: str = "local") -> bool:
        """Publish one epoch's full artifact bytes. Returns False for
        stale epochs (<= latest) or a source outranked by what already
        feeds this distributor."""
        epoch = int(epoch)
        rank = _SOURCE_RANK.get(source, 0)
        with self._lock:
            if rank < self._source_rank:
                incr_counter("distrib", "publish_ignored")
                return False
            if rank > self._source_rank and self._epochs:
                # Source upgrade (a fleet leader's merged artifact
                # taking over from this worker's own builds): the two
                # sources number epochs independently, so the store
                # restarts clean in the new epoch space.
                self._epochs.clear()
                self._links.clear()
                self._anchors = []
                self._encoded.clear()
            latest = max(self._epochs) if self._epochs else None
            if latest is not None and epoch <= latest:
                incr_counter("distrib", "publish_ignored")
                return False
            if latest is not None and self._epochs[latest].sha256 \
                    == hashlib.sha256(blob).hexdigest():
                # Content-unchanged republish (the fleet tick fans the
                # same merged artifact out every epoch): a no-op, so
                # store epochs advance only when bytes change — warm
                # clients keep revalidating 304 against the same ETag
                # and the delta chain never accumulates empty links
                # (which would burn maxDeltaChain and force pointless
                # full-snapshot anchors).
                incr_counter("distrib", "publish_ignored")
                return False
            self._source_rank = rank
            art = None
            cont, cont_etags = {}, {}
            for kind in self.container_kinds:
                if art is None:
                    from ct_mapreduce_tpu.filter import FilterArtifact

                    art = FilterArtifact.from_bytes(blob)
                cb = containers.encode_container(art, kind)
                cont[kind] = cb
                cont_etags[kind] = etag_of(cb)
            pe = PublishedEpoch(
                epoch=epoch, blob=blob,
                sha256=hashlib.sha256(blob).hexdigest(),
                etag=etag_of(blob), created_wall=time.time(),
                containers=cont, container_etags=cont_etags)
            if latest is not None:
                links_since_anchor = self._links_since_anchor()
                if (links_since_anchor >= self.max_chain
                        or self._epochs[latest].blob[:8] != blob[:8]):
                    # Mandatory full-snapshot anchor: chain budget
                    # exhausted, or the artifact format changed under
                    # us (an fl01→fl02 rollover can never delta — the
                    # codec refuses mixed ends); older clients
                    # full-pull from here.
                    self._anchors.append(epoch)
                    incr_counter("distrib", "anchor")
                else:
                    prev = self._epochs[latest]
                    dblob = deltas.compute_delta(
                        prev.blob, blob, latest, epoch)
                    link = deltas.ChainLink(
                        from_epoch=latest, to_epoch=epoch,
                        sha256=hashlib.sha256(dblob).hexdigest(),
                        base_sha256=prev.sha256,
                        target_sha256=pe.sha256, n_bytes=len(dblob))
                    self._links[latest] = (link, dblob)
                    add_sample("distrib", "delta_bytes",
                               value=float(len(dblob)))
            else:
                # The very first publish is an anchor by definition.
                self._anchors.append(epoch)
            self._epochs[epoch] = pe
            self._evict_locked()
            set_gauge("distrib", "epochs_held",
                      value=float(len(self._epochs)))
            set_gauge("distrib", "chain_links",
                      value=float(len(self._links)))
            set_gauge("distrib", "artifact_bytes",
                      value=float(len(blob)))
        incr_counter("distrib", "publish")
        return True

    def _links_since_anchor(self) -> int:
        anchor = max(self._anchors) if self._anchors else -1
        return sum(1 for f in self._links if f >= anchor)

    def _evict_locked(self) -> None:
        while len(self._epochs) > self.history:
            oldest = min(self._epochs)
            del self._epochs[oldest]
            self._links.pop(oldest, None)
            self._anchors = [a for a in self._anchors
                             if a in self._epochs or a > oldest]
            for key in [k for k in self._encoded
                        if k[0] in ("full", "container")
                        and k[1] == oldest
                        or k[0] == "delta" and k[1] == oldest]:
                del self._encoded[key]

    # -- reads -----------------------------------------------------------
    def latest(self) -> Optional[PublishedEpoch]:
        with self._lock:
            if not self._epochs:
                return None
            return self._epochs[max(self._epochs)]

    def get(self, epoch: int) -> Optional[PublishedEpoch]:
        with self._lock:
            return self._epochs.get(int(epoch))

    def delta_bundle(self, from_epoch: int,
                     to_epoch: int) -> Optional[bytes]:
        """The concatenated (self-delimiting) link blobs from → to, or
        None when no contiguous chain exists (evicted epoch, anchor in
        the span, or unknown epochs) — the client then full-pulls."""
        with self._lock:
            manifest = self._manifest_locked()
            path = manifest.link_path(int(from_epoch), int(to_epoch))
            if path is None:
                return None
            return b"".join(self._links[li.from_epoch][1] for li in path)

    def _manifest_locked(self) -> deltas.ChainManifest:
        latest = max(self._epochs) if self._epochs else -1
        pe = self._epochs.get(latest)
        # The chain's delta format follows the published artifact
        # format (CTMRFL02 epochs link as CTMRDL02); an empty store
        # reports the legacy default.
        fmt = deltas.MAGIC.decode()
        if pe is not None and pe.blob[:8] == b"CTMRFL02":
            fmt = deltas.MAGIC_DL02.decode()
        return deltas.ChainManifest(
            latest_epoch=latest,
            latest_sha256=pe.sha256 if pe else "",
            latest_bytes=len(pe.blob) if pe else 0,
            anchors=sorted(self._anchors),
            links=[li for _, (li, _) in sorted(self._links.items())],
            fmt=fmt)

    def manifest(self) -> dict:
        """The chain-manifest JSON body (``GET /filter/manifest``),
        plus the epochs/containers/encodings this worker can serve."""
        with self._lock:
            body = self._manifest_locked().to_json()
            body["containers"] = sorted(self.container_kinds)
            body["encodings"] = list(available_encodings())
            body["epochsHeld"] = sorted(self._epochs)
            body["maxDeltaChain"] = self.max_chain
            return body

    # -- wire encodings --------------------------------------------------
    def encoded(self, cache_key: Optional[tuple], blob: bytes,
                encoding: Optional[str]) -> bytes:
        """``blob`` compressed as ``encoding`` (None = identity), built
        once and cached under ``cache_key + (encoding,)``. A None
        cache_key compresses WITHOUT caching (ad-hoc payloads like
        per-group slices — unbounded key spaces must not grow the
        cache; epoch-keyed entries are pruned with their epoch)."""
        if not encoding:
            return blob
        if cache_key is None:
            return compress(blob, encoding)
        key = tuple(cache_key) + (encoding,)
        with self._lock:
            hit = self._encoded.get(key)
            if hit is not None:
                return hit
        enc = compress(blob, encoding)
        with self._lock:
            self._encoded.setdefault(key, enc)
        return enc

    def stats(self) -> dict:
        with self._lock:
            latest = max(self._epochs) if self._epochs else None
            return {
                "distrib_epochs": sorted(self._epochs),
                "distrib_latest_epoch": latest,
                "distrib_links": len(self._links),
                "distrib_anchors": sorted(self._anchors),
                "distrib_encodings": list(available_encodings()),
            }


def negotiate_encoding(accept_encoding: str) -> Optional[str]:
    """Pick the response Content-Encoding from an Accept-Encoding
    header: zstd when the build has it and the client accepts it, else
    gzip, else identity (None). Tokens with ``q=0`` are treated as
    refused; anything unparseable falls back to identity."""
    accepted = {}
    for part in (accept_encoding or "").split(","):
        token, _, params = part.strip().partition(";")
        token = token.strip().lower()
        if not token:
            continue
        q = 1.0
        params = params.strip()
        if params.startswith("q="):
            try:
                q = float(params[2:])
            except ValueError:
                q = 1.0
        accepted[token] = q
    for enc in available_encodings():
        if accepted.get(enc, accepted.get("*", 0.0)) > 0.0:
            return enc
    return None
