"""Epoch deltas between filter artifacts: the ``CTMRDL01`` stash/diff
format (ROADMAP item 4 — "a client pulls KBs, not the full cascade").

A delta is computed between two consecutive epochs' deterministic
artifact bytes (docs/FILTER_FORMAT.md) and captures exactly what
changed at the group level. Two wire magics, one codec: ``CTMRDL01``
links take ``CTMRFL01`` artifacts to ``CTMRFL01`` artifacts, and
``CTMRDL02`` links do the same for ``CTMRFL02`` — the record formats
are identical; the magic pins which artifact format the replay
re-serializes under (mixed-format deltas are a loud
:class:`DeltaError`, never a guess). The practical difference is
upstream of the codec: per-group-universe ``CTMRFL02`` artifacts
confine churn to the touched groups, so untouched groups diff equal
and ship ZERO bytes — no sparse-XOR salvage of globally-reshaped
layers needed (the CTMRDL01 structural floor BENCHLOG r19 measured).

- **removed** — (issuer, expDate) groups present in the base but not
  the target;
- **added** — groups new in the target, shipped whole (layer records
  identical to the full format's, bitmaps in the delta payload);
- **patched** — groups present in both with different content: the new
  group directory entry plus per-layer diffs. A layer whose bitmap
  size ``m`` is unchanged ships as a sparse XOR record (changed word
  indices + XOR values); a layer whose geometry changed (cascade depth
  or ``m`` moved with the group's serial count) ships whole.

:func:`apply_delta` replays a delta onto the base artifact and
re-serializes through :meth:`FilterArtifact.to_bytes` — the SAME
canonical writer the full build uses — so a replayed chain is
byte-identical to the full build by construction, and both ends are
pinned by mandatory SHA-256 checks (``baseSha256``/``targetSha256``
in the header; a corrupted or misordered link can never produce a
silently wrong filter).

Chains are described by a :class:`ChainManifest`: one link per
consecutive epoch pair with the link blob's own SHA-256, plus the
anchor epochs where a full snapshot is mandatory (``max_chain`` bounds
how many links a client may ever need to replay). The manifest is the
integrity root a client validates a downloaded chain against.

Everything here is deterministic — identical inputs always serialize
to identical delta bytes (ctmrlint's determinism rule covers this
module; no wall-clock, no unsorted iteration).
"""

from __future__ import annotations

import hashlib
import json
import struct
from dataclasses import dataclass, field

import numpy as np

from ct_mapreduce_tpu.filter.artifact import (
    FORMAT_FL01,
    FORMAT_FL02,
    FilterArtifact,
    FilterGroup,
)
from ct_mapreduce_tpu.filter.cascade import BloomLayer, FilterCascade
from ct_mapreduce_tpu.telemetry.metrics import incr_counter, measure

MAGIC = b"CTMRDL01"
MAGIC_DL02 = b"CTMRDL02"
VERSION = 1

# Artifact format ↔ delta wire magic. The delta magic is a pure
# function of the artifact format at both ends (compute_delta refuses
# mixed ends), so a reader knows the replay's serialization format
# from the first 8 bytes.
_DELTA_MAGIC = {FORMAT_FL01: MAGIC, FORMAT_FL02: MAGIC_DL02}
_MAGIC_DELTA_FMT = {MAGIC: FORMAT_FL01, MAGIC_DL02: FORMAT_FL02}

# Default bound on consecutive delta links before a mandatory
# full-snapshot anchor (the `maxDeltaChain` directive).
DEFAULT_MAX_CHAIN = 4


class DeltaError(ValueError):
    """A delta that cannot be (safely) applied: wrong magic/version,
    base mismatch, or a target-hash check failure."""


def artifact_sha256(blob: bytes) -> str:
    return hashlib.sha256(blob).hexdigest()


def _group_entry(g: FilterGroup, payload: bytearray) -> dict:
    """One full group record (layers appended to ``payload``) — the
    same shape as the full format's directory entries."""
    layers = []
    for layer in g.cascade.layers:
        raw = layer.words.astype("<u4").tobytes()
        layers.append({"k": layer.k, "m": layer.m,
                       "off": len(payload), "words": len(raw)})
        payload += raw
    return {
        "expDate": g.exp_id, "expHour": g.exp_hour, "issuer": g.issuer,
        "layers": layers, "n": g.n, "ordinal": g.ordinal,
    }


def _layer_diff(old: BloomLayer | None, new: BloomLayer,
                payload: bytearray) -> dict:
    """Per-layer diff record. Same-geometry layers ship sparse XOR
    words; anything else ships the whole new bitmap."""
    if old is not None and old.m == new.m and old.k == new.k:
        x = old.words.astype(np.uint32) ^ new.words.astype(np.uint32)
        idx = np.nonzero(x)[0].astype(np.uint32)
        # Sparse only pays while the index+value pairs undercut the
        # full bitmap (8 B/changed word vs 4 B/word full).
        if idx.size * 8 < new.words.size * 4:
            off = len(payload)
            payload += idx.astype("<u4").tobytes()
            payload += x[idx].astype("<u4").tobytes()
            return {"mode": "xor", "m": new.m, "k": new.k,
                    "off": off, "count": int(idx.size)}
    raw = new.words.astype("<u4").tobytes()
    off = len(payload)
    payload += raw
    return {"mode": "full", "m": new.m, "k": new.k,
            "off": off, "words": len(raw)}


def compute_delta(base: bytes, target: bytes,
                  from_epoch: int, to_epoch: int) -> bytes:
    """``CTMRDL01`` bytes taking the base epoch's full artifact to the
    target epoch's. Pure function of its inputs (the determinism
    contract of every artifact writer in this tree)."""
    with measure("distrib", "delta_build_s"):
        base_art = FilterArtifact.from_bytes(base)
        target_art = FilterArtifact.from_bytes(target)
        if base_art.fmt != target_art.fmt:
            raise DeltaError(
                f"delta endpoints in different artifact formats "
                f"({base_art.fmt} -> {target_art.fmt}): re-anchor with "
                f"a full snapshot instead of a delta")
        payload = bytearray()
        removed = sorted(set(base_art.groups) - set(target_art.groups))
        added, patched = [], []
        for key in sorted(target_art.groups):
            new_g = target_art.groups[key]
            old_g = base_art.groups.get(key)
            if old_g is None:
                added.append(_group_entry(new_g, payload))
                continue
            if _groups_equal(old_g, new_g):
                continue
            layers = []
            for i, layer in enumerate(new_g.cascade.layers):
                old_layer = (old_g.cascade.layers[i]
                             if i < len(old_g.cascade.layers) else None)
                layers.append(_layer_diff(old_layer, layer, payload))
            patched.append({
                "expDate": new_g.exp_id, "expHour": new_g.exp_hour,
                "issuer": new_g.issuer, "layers": layers,
                "n": new_g.n, "ordinal": new_g.ordinal,
            })
        header = json.dumps({
            "added": added,
            "baseSha256": artifact_sha256(base),
            "fpRate": target_art.fp_rate,
            "fromEpoch": int(from_epoch),
            "patched": patched,
            "payloadBytes": len(payload),
            "removed": [list(k) for k in removed],
            "targetSha256": artifact_sha256(target),
            "toEpoch": int(to_epoch),
            "version": VERSION,
        }, sort_keys=True, separators=(",", ":")).encode()
        incr_counter("distrib", "delta_groups_shipped",
                     value=float(len(added) + len(patched)))
    return (_DELTA_MAGIC[target_art.fmt] + struct.pack("<I", len(header))
            + header + bytes(payload))


def _groups_equal(a: FilterGroup, b: FilterGroup) -> bool:
    if (a.exp_hour, a.ordinal, a.n) != (b.exp_hour, b.ordinal, b.n):
        return False
    if len(a.cascade.layers) != len(b.cascade.layers):
        return False
    for la, lb in zip(a.cascade.layers, b.cascade.layers):
        if (la.m, la.k) != (lb.m, lb.k) or not np.array_equal(
                la.words, lb.words):
            return False
    return True


def delta_format(blob: bytes) -> str:
    """The artifact format (``fl01`` | ``fl02``) a delta blob's replay
    re-serializes under, from its wire magic."""
    fmt = _MAGIC_DELTA_FMT.get(blob[:8])
    if fmt is None:
        raise DeltaError(
            f"not a ct-mapreduce filter delta (magic {blob[:8]!r})")
    return fmt


def parse_delta(blob: bytes) -> tuple[dict, bytes]:
    """(header, payload) of one delta blob (either magic); loud on
    wrong magic or an unknown version (readers must never guess)."""
    delta_format(blob)
    (hlen,) = struct.unpack("<I", blob[8:12])
    header = json.loads(blob[12:12 + hlen].decode())
    if header.get("version") != VERSION:
        raise DeltaError(f"unsupported delta version "
                         f"{header.get('version')!r} (this build reads "
                         f"{VERSION})")
    payload = blob[12 + hlen:]
    if len(payload) != header["payloadBytes"]:
        raise DeltaError(
            f"truncated delta payload: {len(payload)} of "
            f"{header['payloadBytes']} bytes")
    return header, payload


def split_bundle(blob: bytes) -> list[bytes]:
    """Split a concatenation of self-delimiting delta blobs (the
    ``/filter/delta/<from>/<to>`` wire shape) back into links."""
    out = []
    pos = 0
    while pos < len(blob):
        if blob[pos:pos + 8] not in _MAGIC_DELTA_FMT:
            raise DeltaError(f"bundle desync at byte {pos}")
        (hlen,) = struct.unpack("<I", blob[pos + 8:pos + 12])
        header = json.loads(blob[pos + 12:pos + 12 + hlen].decode())
        end = pos + 12 + hlen + int(header["payloadBytes"])
        if end > len(blob):
            raise DeltaError("truncated bundle")
        out.append(blob[pos:end])
        pos = end
    return out


def _layers_from_entry(entry: dict, payload: bytes) -> list[BloomLayer]:
    layers = []
    for lyr in entry["layers"]:
        raw = payload[lyr["off"]: lyr["off"] + lyr["words"]]
        layers.append(BloomLayer(
            m=lyr["m"], k=lyr["k"],
            words=np.frombuffer(raw, dtype="<u4").astype(np.uint32)))
    return layers


def apply_delta(base: bytes, delta: bytes) -> bytes:
    """Replay one delta onto the base artifact's bytes. The result is
    re-serialized through the canonical full-format writer and checked
    against the header's ``targetSha256`` — the output is either
    byte-identical to the full build or a loud :class:`DeltaError`."""
    header, payload = parse_delta(delta)
    fmt = delta_format(delta)
    if artifact_sha256(base) != header["baseSha256"]:
        raise DeltaError(
            f"delta base mismatch: have {artifact_sha256(base)[:16]}…, "
            f"delta expects {header['baseSha256'][:16]}… (epoch "
            f"{header['fromEpoch']})")
    art = FilterArtifact.from_bytes(base)
    if art.fmt != fmt:
        raise DeltaError(
            f"delta format mismatch: base artifact is {art.fmt}, link "
            f"replays {fmt}")
    groups = {(g.issuer, g.exp_id): g
              for _, g in sorted(art.groups.items())}
    for key in header["removed"]:
        groups.pop(tuple(key), None)
    for entry in header["added"]:
        g = FilterGroup(
            issuer=entry["issuer"], exp_id=entry["expDate"],
            exp_hour=int(entry["expHour"]), ordinal=int(entry["ordinal"]),
            n=int(entry["n"]),
            cascade=FilterCascade(
                fp_rate=header["fpRate"], n_included=int(entry["n"]),
                layers=_layers_from_entry(entry, payload)))
        groups[(g.issuer, g.exp_id)] = g
    for entry in header["patched"]:
        key = (entry["issuer"], entry["expDate"])
        old_g = groups.get(key)
        if old_g is None:
            raise DeltaError(f"patched group {key} absent from base")
        layers = []
        for i, lyr in enumerate(entry["layers"]):
            if lyr["mode"] == "full":
                raw = payload[lyr["off"]: lyr["off"] + lyr["words"]]
                words = np.frombuffer(raw, dtype="<u4").astype(np.uint32)
            elif lyr["mode"] == "xor":
                count = int(lyr["count"])
                idx_raw = payload[lyr["off"]: lyr["off"] + 4 * count]
                xor_raw = payload[lyr["off"] + 4 * count:
                                  lyr["off"] + 8 * count]
                idx = np.frombuffer(idx_raw, dtype="<u4").astype(np.int64)
                xor = np.frombuffer(xor_raw, dtype="<u4")
                if i >= len(old_g.cascade.layers):
                    raise DeltaError(
                        f"xor layer {i} of {key} has no base layer")
                words = old_g.cascade.layers[i].words.astype(np.uint32)
                words = words.copy()
                words[idx] ^= xor.astype(np.uint32)
            else:
                raise DeltaError(f"unknown layer mode {lyr['mode']!r}")
            layers.append(BloomLayer(m=lyr["m"], k=lyr["k"], words=words))
        groups[key] = FilterGroup(
            issuer=entry["issuer"], exp_id=entry["expDate"],
            exp_hour=int(entry["expHour"]), ordinal=int(entry["ordinal"]),
            n=int(entry["n"]),
            cascade=FilterCascade(
                fp_rate=header["fpRate"], n_included=int(entry["n"]),
                layers=layers))
    out = FilterArtifact(
        fp_rate=header["fpRate"],
        groups=[groups[k] for k in sorted(groups)], fmt=fmt).to_bytes()
    got = artifact_sha256(out)
    if got != header["targetSha256"]:
        raise DeltaError(
            f"delta replay hash mismatch: built {got[:16]}…, header "
            f"says {header['targetSha256'][:16]}… (corrupt link?)")
    return out


def apply_chain(base: bytes, deltas: list[bytes]) -> bytes:
    """Replay a chain of consecutive deltas (each link's base check
    enforces the order; each link's target check enforces content)."""
    cur = base
    for d in deltas:
        cur = apply_delta(cur, d)
    return cur


# -- chain manifest -------------------------------------------------------


@dataclass
class ChainLink:
    from_epoch: int
    to_epoch: int
    sha256: str  # of the delta blob itself
    base_sha256: str  # of the from-epoch full artifact
    target_sha256: str  # of the to-epoch full artifact
    n_bytes: int

    def to_json(self) -> dict:
        return {"baseSha256": self.base_sha256, "bytes": self.n_bytes,
                "fromEpoch": self.from_epoch, "sha256": self.sha256,
                "targetSha256": self.target_sha256,
                "toEpoch": self.to_epoch}

    @classmethod
    def from_json(cls, d: dict) -> "ChainLink":
        return cls(from_epoch=int(d["fromEpoch"]),
                   to_epoch=int(d["toEpoch"]), sha256=d["sha256"],
                   base_sha256=d["baseSha256"],
                   target_sha256=d["targetSha256"],
                   n_bytes=int(d["bytes"]))


@dataclass
class ChainManifest:
    """The client-facing integrity root of the delta plane: every
    published link with its own SHA-256, the anchor epochs (full
    snapshots a chain may never cross), and the latest epoch's full
    artifact hash. A client at epoch E validates: (1) a contiguous
    link path E → latest exists, (2) each downloaded link hashes to
    its manifest entry, (3) the replayed bytes hash to
    ``latest_sha256``."""

    latest_epoch: int = -1
    latest_sha256: str = ""
    latest_bytes: int = 0
    anchors: list[int] = field(default_factory=list)
    links: list[ChainLink] = field(default_factory=list)
    # The chain's delta wire format ("CTMRDL01" | "CTMRDL02") — every
    # link in one manifest shares it (compute_delta refuses mixed
    # ends, so a format rev always re-anchors).
    fmt: str = "CTMRDL01"

    def to_json(self) -> dict:
        return {
            "anchors": sorted(self.anchors),
            "format": self.fmt,
            "latestBytes": self.latest_bytes,
            "latestEpoch": self.latest_epoch,
            "latestSha256": self.latest_sha256,
            "links": [li.to_json() for li in
                      sorted(self.links,
                             key=lambda li: (li.from_epoch, li.to_epoch))],
            "version": VERSION,
        }

    @classmethod
    def from_json(cls, d: dict) -> "ChainManifest":
        return cls(latest_epoch=int(d["latestEpoch"]),
                   latest_sha256=d["latestSha256"],
                   latest_bytes=int(d["latestBytes"]),
                   anchors=[int(a) for a in d["anchors"]],
                   links=[ChainLink.from_json(li) for li in d["links"]],
                   fmt=d.get("format", MAGIC.decode()))

    def link_path(self, from_epoch: int,
                  to_epoch: int) -> list[ChainLink] | None:
        """The contiguous link sequence from → to, or None when the
        path is broken (epoch evicted, or an anchor sits strictly
        inside the span — anchored clients must full-pull)."""
        if from_epoch >= to_epoch:
            return None
        by_from = {li.from_epoch: li for li in self.links}
        path = []
        cur = from_epoch
        while cur < to_epoch:
            li = by_from.get(cur)
            if li is None:
                return None
            if li.from_epoch != from_epoch and li.from_epoch in self.anchors:
                return None  # chains never cross an anchor
            path.append(li)
            cur = li.to_epoch
        return path if cur == to_epoch else None

    def validate_chain(self, from_epoch: int, to_epoch: int,
                       deltas: list[bytes]) -> list[ChainLink]:
        """Check downloaded link blobs against the manifest before any
        replay: path contiguity and per-link SHA-256. Returns the
        matching links; raises :class:`DeltaError` on any mismatch
        (truncated, corrupted, or reordered downloads die here)."""
        path = self.link_path(from_epoch, to_epoch)
        if path is None:
            raise DeltaError(
                f"no delta path {from_epoch} -> {to_epoch} in manifest")
        if len(deltas) != len(path):
            raise DeltaError(
                f"chain length mismatch: {len(deltas)} blobs for "
                f"{len(path)} manifest links")
        for li, blob in zip(path, deltas):
            got = hashlib.sha256(blob).hexdigest()
            if got != li.sha256:
                raise DeltaError(
                    f"link {li.from_epoch}->{li.to_epoch} hash mismatch: "
                    f"downloaded {got[:16]}…, manifest {li.sha256[:16]}…")
        return path
