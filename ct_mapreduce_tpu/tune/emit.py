"""Tuned-profile emission: search results → the config layer's food.

The output is exactly the file ``config/profile.py`` loads (version 1,
``knobs.<section>.<knob>``), plus the two round-21 blocks:

- ``fingerprint``: the platform this profile was measured on
  (:func:`~ct_mapreduce_tpu.config.profile.current_fingerprint`), so
  the loader refuses to apply it elsewhere;
- ``provenance``: per-section, per-measurement evidence — the swept
  point that won, the measured 1-D curves through it, rep counts and
  harness wall — for humans and ``ctmr-tune show``, ignored by
  resolution.

Determinism: bytes are a function of the measurements alone — sorted
keys, fixed separators, no timestamps, no hostnames, no RNG (the
"no Date.now analogs in emitted bytes" rule; measured walls are data,
a *current time* would be a build stamp). Writes are atomic
(tmp + rename) so a preempted campaign never leaves a half profile.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from ct_mapreduce_tpu.config import profile as platprofile
from ct_mapreduce_tpu.tune.registry import SWEEPABLE


def tuned_knobs(section: str, best_point: dict) -> dict:
    """The emit-able slice of a search's best point: only knobs the
    registry declares sweepable for the section carry into the
    profile (extra swept axes — maxBatch, offered rate — are
    measurement parameters, not profile knobs)."""
    allowed = SWEEPABLE.get(section, {})
    return {k: v for k, v in best_point.items() if k in allowed}


def build_profile(results: list, platform: str = "",
                  fingerprint: Optional[dict] = None) -> dict:
    """Assemble the profile dict from ``(measurement, SearchResult)``
    pairs (measurement supplies section/metric/unit identity)."""
    fp = (dict(fingerprint) if fingerprint is not None
          else platprofile.current_fingerprint())
    if not platform:
        platform = "-".join(
            str(fp[k]) for k in ("jax_backend", "device_kind",
                                 "device_count") if k in fp) or "host"
    knobs: dict = {}
    provenance: dict = {}
    for m, sr in results:
        # NaN best_value = the search never confirmed a feasible
        # point: nothing to tune from, and NaN must never reach the
        # emitted bytes (it is not strict JSON).
        confirmed = sr.best_value == sr.best_value
        tuned = tuned_knobs(m.section, sr.best) if confirmed else {}
        if tuned:
            knobs.setdefault(m.section, {}).update(tuned)
        provenance.setdefault(m.section, {})[m.name] = {
            "metric": m.metric,
            "unit": m.unit,
            "best_point": dict(sr.best),
            "best_value": (round(float(sr.best_value), 3)
                           if confirmed else None),
            "curves": {k: [[v, round(float(y), 3)] for v, y in c]
                       for k, c in sr.curves.items()},
            "evals": len(sr.evaluations),
            "reps": sum(n for _, n, _ in sr.evaluations),
            "wall_s": round(float(sr.wall_s), 3),
            "budget_exhausted": bool(sr.budget_exhausted),
        }
    return {
        "version": platprofile.PROFILE_VERSION,
        "platform": platform,
        "fingerprint": fp,
        "knobs": knobs,
        "provenance": provenance,
    }


def profile_bytes(profile: dict) -> bytes:
    return (json.dumps(profile, sort_keys=True, indent=1,
                       separators=(",", ": ")) + "\n").encode()


def write_profile(path: str, profile: dict) -> str:
    """Atomic write (tmp + rename + fsync) and cache invalidation so
    a resolve through the same path immediately sees the new bytes."""
    blob = profile_bytes(profile)
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(blob)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    platprofile.invalidate_cache(path)
    return path
