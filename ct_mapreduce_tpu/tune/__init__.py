"""ct_mapreduce_tpu.tune: the knob autotuner (ROADMAP item 1, round 21).

Four layers, each usable alone:

- :mod:`tune.harness` — the shared measurement discipline (warmup
  excluded but recorded, best-of-N reps, synchronous readbacks,
  parity asserted at every swept point) that previously lived
  duplicated inside ``tools/stagecost.py`` and ``tools/qps_sweep.py``.
- :mod:`tune.measure` — the measurement registry: every bench surface
  (staged-queue e2e, serve open-loop, verify lanes/s, fleet
  entries/s, filter build rate) wrapped as a uniform
  :class:`~ct_mapreduce_tpu.tune.measure.Measurement` provider with
  structured :class:`~ct_mapreduce_tpu.tune.measure.MeasureResult`\\ s.
- :mod:`tune.search` — coordinate descent + successive halving over a
  declared knob grid: wall/eval budgeted, deterministic given a seed.
- :mod:`tune.emit` — versioned tuned-profile JSON keyed by the
  platform fingerprint (config/profile.py loads it back through the
  knob ladder) with per-knob measurement provenance.

:mod:`tune.registry` declares which knobs are sweepable (with their
ladders) and which are excluded with a justification — the
config-parity lint rule enforces that every ``Knob`` spec in the tree
appears in exactly one of the two.
"""

from ct_mapreduce_tpu.tune.measure import (  # noqa: F401
    Measurement,
    MeasureResult,
    get_measurement,
    measurements,
    register,
)
from ct_mapreduce_tpu.tune.search import (  # noqa: F401
    EvalResult,
    SearchResult,
    coordinate_descent,
)
