"""ctmr-tune: the knob ladder made visible, and the sweep driver.

``ctmr-tune show`` closes the debuggability gap the round-18 loader
left open: for every profile section it prints each knob's RESOLVED
value and which layer won (explicit / env / profile / default), plus
the active profile's path and fingerprint — so "why is K still 1 on
this host" is one command, not a source dive.

``ctmr-tune sweep`` runs the search driver over one or more
measurement providers and emits the tuned profile
(tools/campaign.py wraps this into the resumable device campaign).
"""

from __future__ import annotations

import argparse
import json
import sys


def _show(args) -> int:
    import importlib

    from ct_mapreduce_tpu.config import profile as platprofile
    from ct_mapreduce_tpu.tune import registry

    if args.profile:
        platprofile.set_active_profile(args.profile)
    path = platprofile.active_profile_path()
    prof = platprofile.load_profile(path) if path else None
    print(f"platformProfile: {path or '(none)'}"
          + ("" if not path else
             " [loaded]" if prof else " [IGNORED — see stderr]"))
    if prof is not None:
        fp = prof.get("fingerprint") or {}
        if fp:
            cur = platprofile.current_fingerprint()
            ok = platprofile.fingerprint_matches(fp, cur)
            print(f"  fingerprint: {json.dumps(fp, sort_keys=True)} "
                  f"({'matches this host' if ok else 'MISMATCH'})")
        else:
            print("  fingerprint: (none — profile predates round 21)")
        if prof.get("platform"):
            print(f"  platform: {prof['platform']}")
    explicit = {}
    if args.config:
        from ct_mapreduce_tpu.config.config import CTConfig

        cfg = CTConfig.load(["-config", args.config])
        # Directive spelling -> the loaded field value: the explicit
        # layer speaks knob names (chunksPerDispatch), not field names.
        for directive, (fld, _typ) in CTConfig._DIRECTIVES.items():
            v = getattr(cfg, fld, None)
            if v is not None:
                explicit[directive] = v
    for section, (mod_name, attr) in registry.SECTIONS.items():
        try:
            knobs = getattr(importlib.import_module(mod_name), attr)
        except Exception as err:
            print(f"[{section}] unavailable: {err}", file=sys.stderr)
            continue
        print(f"[{section}]")
        rows = platprofile.explain_section(
            section, knobs,
            {k.name: explicit.get(k.name) for k in knobs})
        for name, row in rows.items():
            swept = name in registry.SWEEPABLE.get(section, {})
            tag = "sweepable" if swept else "excluded"
            print(f"  {name} = {row['value']!r}  "
                  f"({row['layer']}; {tag})")
    return 0


def _sweep(args) -> int:
    from ct_mapreduce_tpu.tune import emit, measure, search

    names = [n for n in args.measure.split(",") if n]
    results = []
    for name in names:
        m = measure.get_measurement(name)
        grid = m.grid(args.scale)
        print(f"# sweep {name} ({m.section}): grid "
              f"{json.dumps(grid)}", file=sys.stderr)
        sr = search.coordinate_descent(
            grid, m.evaluator(args.scale), maximize=m.maximize,
            seed=args.seed, budget_evals=args.budget_evals,
            budget_wall_s=args.budget_wall_s,
            reps=(args.reps_lo, args.reps_hi))
        print(f"# best {name}: {json.dumps(sr.best)} -> "
              f"{sr.best_value:.1f} {m.unit} "
              f"({len(sr.evaluations)} evals, {sr.wall_s:.1f}s"
              f"{', budget exhausted' if sr.budget_exhausted else ''})",
              file=sys.stderr)
        results.append((m, sr))
    profile = emit.build_profile(results, platform=args.platform)
    if args.out:
        emit.write_profile(args.out, profile)
        print(f"# wrote {args.out}", file=sys.stderr)
    json.dump(profile, sys.stdout, sort_keys=True, indent=1)
    print()
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ctmr-tune")
    sub = ap.add_subparsers(dest="cmd")
    shw = sub.add_parser("show", help="dump the resolved knob ladder")
    shw.add_argument("--profile", default="",
                     help="profile path (else platformProfile / "
                     "CTMR_PLATFORM_PROFILE)")
    shw.add_argument("--config", default="",
                     help="ct-fetch ini supplying the explicit layer")
    sw = sub.add_parser("sweep", help="search the knob grid and emit "
                        "a tuned profile")
    sw.add_argument("--measure", required=True,
                    help="comma-separated measurement names "
                    "(see tune/measure.py)")
    sw.add_argument("--scale", default="smoke",
                    choices=("smoke", "full"))
    sw.add_argument("--out", default="", help="profile output path")
    sw.add_argument("--platform", default="", help="profile label")
    sw.add_argument("--seed", type=int, default=0)
    sw.add_argument("--budget-evals", type=int, default=0)
    sw.add_argument("--budget-wall-s", type=float, default=0.0)
    sw.add_argument("--reps-lo", type=int, default=1)
    sw.add_argument("--reps-hi", type=int, default=3)
    args = ap.parse_args(argv)
    if args.cmd == "sweep":
        return _sweep(args)
    if args.cmd != "show":
        args = shw.parse_args([])  # default to `show` with defaults
    return _show(args)


if __name__ == "__main__":
    sys.exit(main())
