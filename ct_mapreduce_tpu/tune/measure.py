"""Measurement registry: every bench surface as a uniform provider.

A :class:`Measurement` wraps one existing bench surface — staged-queue
e2e ingest, serve open-loop, the verify lanes, fleet aggregate rate,
the filter device-lane build — behind one contract:

- ``grid(scale)``: the knob axes it sweeps (section knobs use their
  directive spellings so profile emission is a straight copy; extra
  non-profile axes like ``maxBatch`` are swept and recorded in
  provenance but never emitted as knobs);
- ``run(point, reps, scale)``: a :class:`MeasureResult` measured with
  the bench discipline (warmup excluded but recorded in
  ``compile_s``, per-rep values, parity asserted inside the run).

``scale`` is ``"smoke"`` (CPU-box sized: the bench gate and tests) or
``"full"`` (device-campaign sized: tools/campaign.py). Corpora cache
per (provider, scale): the sweep pays setup once, not per point.

Providers import jax and the subsystems lazily — registering and
enumerating measurements is free, so the search driver, the lint rule
and ``ctmr-tune`` never pay device startup just to know what exists.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, Optional

from ct_mapreduce_tpu.telemetry import metrics
from ct_mapreduce_tpu.tune import harness
from ct_mapreduce_tpu.tune.registry import SWEEPABLE
from ct_mapreduce_tpu.tune.search import EvalResult


@dataclass
class MeasureResult:
    """One measured point: the metric (higher is better unless the
    provider says otherwise), its per-rep values and spread, and the
    compile/setup wall excluded from the metric but never hidden."""

    metric: str
    value: float  # best-rep metric value
    unit: str
    reps: int
    values: list = field(default_factory=list)  # per-rep metric values
    std: float = 0.0
    wall_s: float = 0.0
    compile_s: float = 0.0
    feasible: bool = True
    extra: dict = field(default_factory=dict)


class Measurement:
    """Base provider. Subclasses set the identity fields and implement
    :meth:`run`; ``grid`` defaults to the registry's sweepable ladders
    for the provider's section."""

    name = "measurement"
    section = ""
    metric = "rate"
    unit = "1/s"
    maximize = True

    def grid(self, scale: str = "smoke") -> dict:
        return {k: list(v) for k, v in
                SWEEPABLE.get(self.section, {}).items()}

    def run(self, point: dict, reps: int = 3,
            scale: str = "smoke") -> MeasureResult:
        raise NotImplementedError

    def evaluator(self, scale: str = "smoke"
                  ) -> Callable[[dict, int], EvalResult]:
        """Adapt this provider to the search driver's
        ``evaluate(point, reps)`` contract."""
        def evaluate(point: dict, reps: int) -> EvalResult:
            with metrics.measure("tune", "measure_s"):
                mr = self.run(point, reps=reps, scale=scale)
            mean = (sum(mr.values) / len(mr.values)
                    if mr.values else mr.value)
            return EvalResult(mean=mean, std=mr.std, reps=mr.reps,
                              wall_s=mr.wall_s, feasible=mr.feasible)
        return evaluate

    def _result(self, tr: harness.TimedReps, to_metric, **extra
                ) -> MeasureResult:
        """Fold a TimedReps (per-rep seconds) through ``to_metric``
        (seconds -> metric value)."""
        vals = [to_metric(v) for v in tr.values]
        m = sum(vals) / len(vals) if vals else 0.0
        std = ((sum((v - m) ** 2 for v in vals) / (len(vals) - 1)) ** 0.5
               if len(vals) > 1 else 0.0)
        return MeasureResult(
            metric=self.metric, value=max(vals) if vals else 0.0,
            unit=self.unit, reps=len(vals), values=vals, std=std,
            wall_s=tr.wall_s, compile_s=tr.compile_s, extra=dict(extra))


_REGISTRY: dict[str, Measurement] = {}


def register(m) -> Measurement:
    """Register a provider (used as a class decorator: the registry
    holds one shared instance so corpus caches persist across a
    sweep's points)."""
    inst = m() if isinstance(m, type) else m
    _REGISTRY[inst.name] = inst
    return m


def get_measurement(name: str) -> Measurement:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(f"no measurement {name!r}; have "
                       f"{sorted(_REGISTRY)}") from None


def measurements() -> dict:
    return dict(_REGISTRY)


# -- staged-queue e2e -----------------------------------------------------


@register
class StagingE2E(Measurement):
    """chunksPerDispatch × stagingDepth through the REAL ingest sink:
    synthetic wire batches replayed through AggregatorSink (pure-
    python decode for portability), parity of drained counts asserted
    against the first point measured on this corpus."""

    name = "staging_e2e"
    section = "staging"
    metric = "entries_per_s"
    unit = "entries/s"

    _SCALES = {  # chunk lanes, chunks — smoke matches run_smoke's
        # shapes so the jit cache is shared within one process
        "smoke": (1024, 8),
        "full": (4096, 16),
    }

    def __init__(self) -> None:
        self._corpus: dict = {}

    def grid(self, scale: str = "smoke") -> dict:
        g = super().grid(scale)
        if scale == "smoke":
            g["chunksPerDispatch"] = [1, 2]
            g["stagingDepth"] = [1, 2]
        return g

    def _setup(self, scale: str):
        if scale in self._corpus:
            return self._corpus[scale]
        from ct_mapreduce_tpu.ingest.sync import RawBatch
        from ct_mapreduce_tpu.utils import syncerts

        chunk, n_chunks = self._SCALES[scale]
        tpls = [syncerts.make_template(issuer_cn=f"Tune Issuer {k}")
                for k in range(2)]
        raw = []
        for i in range(n_chunks):
            lis, eds = syncerts.make_wire_batch(tpls, i * chunk, chunk)
            raw.append(RawBatch(lis, eds, i * chunk, "tune-log"))
        state = {"chunk": chunk, "n_chunks": n_chunks, "raw": raw,
                 "capacity": 1 << max(14, (2 * chunk * n_chunks)
                                      .bit_length()),
                 "baseline": None}
        self._corpus[scale] = state
        return state

    def run(self, point: dict, reps: int = 3,
            scale: str = "smoke") -> MeasureResult:
        import jax  # noqa: F401  (device stack must exist)

        from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
        from ct_mapreduce_tpu.ingest.sync import AggregatorSink

        st = self._setup(scale)
        total = st["chunk"] * st["n_chunks"]
        k = int(point.get("chunksPerDispatch", 1))
        depth = int(point.get("stagingDepth", 2))
        prev_native = os.environ.get("CTMR_NATIVE")
        os.environ["CTMR_NATIVE"] = "0"  # byte-identical python lane
        try:
            def one_replay():
                agg = TpuAggregator(capacity=st["capacity"],
                                    batch_size=st["chunk"])
                sink = AggregatorSink(agg, flush_size=st["chunk"],
                                      device_queue_depth=depth,
                                      overlap_workers=2,
                                      chunks_per_dispatch=k,
                                      staging_depth=depth)
                try:
                    for rb in st["raw"]:
                        sink.store_raw_batch(rb)
                    sink.flush()
                    snap = agg.drain()
                finally:
                    sink.close()
                return agg._table_fill_exact(), dict(snap.counts)

            def check(res):
                count, counts = res
                if st["baseline"] is None:
                    st["baseline"] = res
                harness.require(
                    res == st["baseline"],
                    f"staging parity: K={k} depth={depth} drained state"
                    f" diverged from the corpus baseline")
                harness.require(
                    count <= total,
                    f"staging: table count {count} exceeds fed {total}")

            tr = harness.timed_reps(one_replay, reps=reps, check=check)
        finally:
            if prev_native is None:
                os.environ.pop("CTMR_NATIVE", None)
            else:
                os.environ["CTMR_NATIVE"] = prev_native
        return self._result(tr, lambda s: total / s, total_entries=total,
                            chunksPerDispatch=k, stagingDepth=depth)


# -- serve open-loop ------------------------------------------------------


@register
class ServeOpenLoop(Measurement):
    """serveReplicas × maxBatch × maxDelayMs at a fixed offered rate,
    open loop, with a background thread ingesting fresh certificates
    into the same aggregator (the p99-under-ingest bound: a point is
    feasible only while p99 and shed stay inside the limits)."""

    name = "serve_openloop"
    section = "serve"
    metric = "achieved_qps"
    unit = "lanes/s"

    _SCALES = {  # entries, table_bits, rate, duration_s, p99_ms limit
        # smoke limits are generous on purpose: a 1-core CI box runs
        # the GIL-sharing ingest thread and 8 dispatchers on one core,
        # so p99 is structurally high there; the bound only has teeth
        # at full scale on a device host.
        "smoke": (8192, 14, 2000.0, 0.4, 1000.0),
        "full": (2_000_000, 22, 120_000.0, 5.0, 50.0),
    }

    def __init__(self) -> None:
        self._corpus: dict = {}

    def grid(self, scale: str = "smoke") -> dict:
        g = super().grid(scale)
        if scale == "smoke":
            g["serveReplicas"] = [1, 2]
            g.update({"maxBatch": [64], "maxDelayMs": [1.0]})
        else:
            g.update({"maxBatch": [256, 1024],
                      "maxDelayMs": [0.5, 1.0, 2.0]})
        return g

    def _setup(self, scale: str):
        if scale not in self._corpus:
            entries, bits = self._SCALES[scale][:2]
            agg, eh = harness.build_aggregator(entries, bits)
            from ct_mapreduce_tpu.utils import syncerts

            tpl = syncerts.make_template(issuer_cn="Tune Serve CA")
            self._corpus[scale] = (agg, eh, tpl)
        return self._corpus[scale]

    def run(self, point: dict, reps: int = 3,
            scale: str = "smoke") -> MeasureResult:
        import threading
        import time as _time

        from ct_mapreduce_tpu.utils import syncerts

        entries, _, rate, duration, p99_lim = self._SCALES[scale]
        agg, eh, tpl = self._setup(scale)
        replicas = int(point.get("serveReplicas", 2))
        max_batch = int(point.get("maxBatch", 256))
        max_delay_s = float(point.get("maxDelayMs", 1.0)) / 1e3
        t_all = _time.perf_counter()
        vals, p99s, sheds = [], [], []
        compile_s = 0.0
        # Background ingest: fresh template certs fold into the SAME
        # table while the open loop probes it. Their fingerprints live
        # under the template's own (issuer, expiry) group, disjoint
        # from the probe domain's (0, eh) keys.
        for rep in range(max(1, int(reps)) + 1):  # +1 warmup
            stop = threading.Event()
            j0 = [0]

            def bg_ingest():
                while not stop.is_set():
                    entries_b = [
                        (syncerts.stamp_serial(tpl, j), tpl.issuer_der)
                        for j in range(j0[0], j0[0] + 256)]
                    agg.ingest(entries_b)
                    j0[0] += 256

            bg = threading.Thread(target=bg_ingest, daemon=True)
            bg.start()
            t0 = _time.perf_counter()
            try:
                r = harness.run_open_loop(
                    agg, eh, entries, rate=rate, duration_s=duration,
                    arrival_batch=16, threads=8, max_batch=max_batch,
                    max_delay_s=max_delay_s, device=True,
                    replicas=replicas, cache_size=4096, zipf=1.2)
            finally:
                stop.set()
                bg.join(timeout=30)
            if rep == 0:  # warmup: oracle build + contains compiles
                compile_s = _time.perf_counter() - t0
                continue
            vals.append(float(r["achieved_qps"]))
            p99s.append(float(r["p99_ms"] or 0.0))
            sheds.append(float(r["shed_frac"]))
        m = sum(vals) / len(vals)
        std = ((sum((v - m) ** 2 for v in vals) / (len(vals) - 1)) ** 0.5
               if len(vals) > 1 else 0.0)
        feasible = max(p99s) <= p99_lim and max(sheds) <= 0.01
        return MeasureResult(
            metric=self.metric, value=max(vals), unit=self.unit,
            reps=len(vals), values=vals, std=std,
            wall_s=_time.perf_counter() - t_all, compile_s=compile_s,
            feasible=feasible,
            extra={"p99_ms": max(p99s), "shed_frac": max(sheds),
                   "offered_qps": rate, "serveReplicas": replicas,
                   "maxBatch": max_batch,
                   "maxDelayMs": max_delay_s * 1e3,
                   "p99_limit_ms": p99_lim})


# -- verify lanes ---------------------------------------------------------


@register
class VerifyLanes(Measurement):
    """verifyBatch × verifyPrecompWindow lanes/s on the batched ECDSA
    kernels, host-verdict parity at every point (the round-17 sweep,
    now registry-driven)."""

    name = "verify_lanes"
    section = "verify"
    metric = "lanes_per_s"
    unit = "lanes/s"

    _SCALES = {"smoke": (16, 3), "full": (64, 7)}  # n_uniq, n_keys

    def __init__(self) -> None:
        self._corpus: dict = {}

    def grid(self, scale: str = "smoke") -> dict:
        g = super().grid(scale)
        if scale == "smoke":
            g["verifyBatch"] = [32]
            g["verifyPrecompWindow"] = [0, 8]
        return g

    def _setup(self, scale: str):
        if scale not in self._corpus:
            from ct_mapreduce_tpu.ops import ecdsa

            n_uniq, n_keys = self._SCALES[scale]
            self._corpus[scale] = harness.verify_corpus(
                ecdsa.P256_OPS, n_uniq, n_keys)
        return self._corpus[scale]

    def run(self, point: dict, reps: int = 3,
            scale: str = "smoke") -> MeasureResult:
        from ct_mapreduce_tpu.ops import ecdsa

        width = int(point.get("verifyBatch", 1024))
        window = int(point.get("verifyPrecompWindow", 8))
        corpus = self._setup(scale)
        tr = harness.verify_point(ecdsa.P256_OPS, width, window, corpus,
                                  reps=reps, verbose=False)
        return self._result(tr, lambda s: width / s,
                            verifyBatch=width,
                            verifyPrecompWindow=window, curve="P-256")


# -- fleet aggregate rate -------------------------------------------------


@register
class FleetRate(Measurement):
    """Aggregate entries/s vs W over the live fleet harness
    (tools/fleet.py: real ct-fetch worker processes under the Redis
    election fabric), serial-reference parity per point. Each worker
    is a subprocess paying full jax startup — smoke sweeps W=1 only;
    the W ladder is the device campaign's."""

    name = "fleet_rate"
    section = "fleet"
    metric = "entries_per_s"
    unit = "entries/s"

    _SCALES = {  # n_logs, entries_per_log
        "smoke": (2, 64),
        "full": (8, 4096),
    }

    def grid(self, scale: str = "smoke") -> dict:
        g = super().grid(scale)
        if scale == "smoke":
            g["numWorkers"] = [1]
        return g

    def run(self, point: dict, reps: int = 3,
            scale: str = "smoke") -> MeasureResult:
        import time as _time

        fleet = _import_fleet_harness()
        n_logs, per_log = self._SCALES[scale]
        workers = int(point.get("numWorkers", 1))
        t_all = _time.perf_counter()
        vals = []
        parity = 1
        for _ in range(max(1, int(reps))):
            r = fleet.run_fleet(workers=workers, n_logs=n_logs,
                                entries_per_log=per_log, verify=True)
            harness.require(r.get("parity") == 1,
                            f"fleet W={workers}: merged snapshot "
                            "diverged from the serial reference")
            parity = r["parity"]
            vals.append(float(r["entries_per_s"]))
        m = sum(vals) / len(vals)
        std = ((sum((v - m) ** 2 for v in vals) / (len(vals) - 1)) ** 0.5
               if len(vals) > 1 else 0.0)
        # Worker jax startup dominates the harness wall and is
        # per-process setup, not throughput: entries_per_s already
        # comes from the fleet's own measured window, so the whole
        # residual wall is the excluded setup cost.
        total = n_logs * per_log
        measured = sum(total / v for v in vals if v > 0)
        return MeasureResult(
            metric=self.metric, value=max(vals), unit=self.unit,
            reps=len(vals), values=vals, std=std,
            wall_s=_time.perf_counter() - t_all,
            compile_s=max(0.0,
                          _time.perf_counter() - t_all - measured),
            extra={"numWorkers": workers, "parity": parity,
                   "entries": total})


# -- filter device-lane build rate ----------------------------------------


@register
class FilterBuild(Measurement):
    """filterStreamChunk × filterFusedLanes build rate through the
    round-19 driver, with the round-15 contract as the parity gate:
    every point's artifact bytes must equal the first point's."""

    name = "filter_build"
    section = "filter"
    metric = "entries_per_s"
    unit = "serials/s"

    _SCALES = {  # n_serials, n_groups
        "smoke": (20_000, 8),
        "full": (2_000_000, 64),
    }

    def __init__(self) -> None:
        self._corpus: dict = {}

    def grid(self, scale: str = "smoke") -> dict:
        g = super().grid(scale)
        if scale == "smoke":
            g["filterStreamChunk"] = [0, 65536]
            g["filterFusedLanes"] = [0, 1024]
            g.pop("filterCaptureSpillMB", None)
        return g

    def _setup(self, scale: str):
        if scale in self._corpus:
            return self._corpus[scale]
        n, n_groups = self._SCALES[scale]
        sets = {}
        for g in range(n_groups):
            lo = g * n // n_groups
            hi = (g + 1) * n // n_groups
            sets[(g % 4, 500_000 + g)] = [
                b"\x01" + j.to_bytes(8, "big") for j in range(lo, hi)]
        state = {"sets": sets, "n": n, "baseline": None}
        self._corpus[scale] = state
        return state

    def run(self, point: dict, reps: int = 3,
            scale: str = "smoke") -> MeasureResult:
        from ct_mapreduce_tpu.filter import artifact as fartifact

        st = self._setup(scale)
        stream_chunk = int(point.get("filterStreamChunk", 0))
        fused_lanes = int(point.get("filterFusedLanes", 0))
        spill_mb = int(point.get("filterCaptureSpillMB", 0))
        if spill_mb:
            os.environ["CTMR_FILTER_SPILL_MB"] = str(spill_mb)

        def build():
            art = fartifact.build_artifact(
                st["sets"], use_device=True,
                stream_chunk=stream_chunk, fused_lanes=fused_lanes)
            return art.to_bytes()

        def check(blob):
            if st["baseline"] is None:
                st["baseline"] = blob
            harness.require(
                blob == st["baseline"],
                f"filter parity: stream_chunk={stream_chunk} "
                f"fused_lanes={fused_lanes} artifact bytes diverged")

        try:
            tr = harness.timed_reps(build, reps=reps, check=check)
        finally:
            if spill_mb:
                os.environ.pop("CTMR_FILTER_SPILL_MB", None)
        return self._result(tr, lambda s: st["n"] / s,
                            n_serials=st["n"],
                            filterStreamChunk=stream_chunk,
                            filterFusedLanes=fused_lanes)


def _import_fleet_harness():
    """tools/fleet.py lives beside the package, not inside it; the
    campaign and bench add the repo root to sys.path, and this mirrors
    their fallback for installed-package contexts."""
    import importlib
    import sys

    try:
        return importlib.import_module("tools.fleet")
    except ImportError:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        if root not in sys.path:
            sys.path.insert(0, root)
        return importlib.import_module("tools.fleet")
