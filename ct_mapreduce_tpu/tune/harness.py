"""Shared measurement harness: the bench-methodology discipline, once.

``tools/stagecost.py`` and ``tools/qps_sweep.py`` each grew their own
copy of the same three habits — a warmup run whose wall is excluded
from the metric but recorded (compile time is real, it just is not
throughput), best-of-N timed reps ending in a synchronous value read,
and a parity assertion against a reference at EVERY swept point (a
number from a diverging configuration is not a measurement). This
module is that harness extracted once; the tools now import it, and
the :mod:`tune.measure` providers build on it.

Everything heavyweight (jax, the aggregator, the serve plane) imports
lazily inside the functions that need it: the search driver and the
campaign's resume machinery must be importable — and testable — with
no device stack at all.
"""

from __future__ import annotations

import os
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Optional


def say(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


class ParityError(AssertionError):
    """A swept point diverged from its reference — the measurement at
    that point is void, and the sweep must not continue past it."""


def require(cond: bool, msg: str) -> None:
    if not cond:
        raise ParityError(msg)


@dataclass
class TimedReps:
    """Structured timing of one measured call: per-rep walls (the
    metric derives from ``best``), plus the warmup wall recorded apart
    — compile/table-build time is excluded from the rate but never
    hidden."""

    values: list = field(default_factory=list)  # per-rep seconds
    compile_s: float = 0.0  # warmup wall (compile + first run)
    wall_s: float = 0.0  # total harness wall incl. warmup

    @property
    def best(self) -> float:
        return min(self.values) if self.values else 0.0

    @property
    def mean(self) -> float:
        return (sum(self.values) / len(self.values)
                if self.values else 0.0)

    @property
    def std(self) -> float:
        if len(self.values) < 2:
            return 0.0
        m = self.mean
        return (sum((v - m) ** 2 for v in self.values)
                / (len(self.values) - 1)) ** 0.5


def timed_reps(fn: Callable[[], object], reps: int = 3,
               warmup: bool = True,
               check: Optional[Callable[[object], None]] = None
               ) -> TimedReps:
    """Run ``fn`` (which must end in a synchronous readback — honest
    timing: dispatch → compute → readback, nothing in flight) once as
    excluded-but-recorded warmup, then ``reps`` timed times.
    ``check`` (e.g. a parity assertion) runs on every return value,
    warmup included."""
    out = TimedReps()
    t_all = time.perf_counter()
    if warmup:
        t0 = time.perf_counter()
        r = fn()
        out.compile_s = time.perf_counter() - t0
        if check is not None:
            check(r)
    for _ in range(max(1, int(reps))):
        t0 = time.perf_counter()
        r = fn()
        out.values.append(time.perf_counter() - t0)
        if check is not None:
            check(r)
    out.wall_s = time.perf_counter() - t_all
    return out


# -- serve-plane harness (moved from tools/qps_sweep.py) ------------------


def build_aggregator(entries: int, table_bits: int):
    """A dedup table pre-filled with ``entries`` synthetic serials
    (8 zero bytes + 8-byte BE counter — :func:`serial_bytes` probes
    the same space), via the bulk reinsert path so setup stays off
    the measured window."""
    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.core import packing

    import numpy as np

    agg = TpuAggregator(capacity=1 << table_bits, batch_size=4096,
                        grow_at=0.0)
    eh = agg.base_hour + 1000
    serials = np.zeros((entries, packing.MAX_SERIAL_BYTES), np.uint8)
    counters = np.arange(entries, dtype=np.uint64)
    for i in range(8):
        serials[:, 15 - i] = ((counters >> np.uint64(8 * i))
                              & np.uint64(0xFF)).astype(np.uint8)
    slen = np.full((entries,), 16, np.int64)
    keys = packing.fingerprints_np(
        np.zeros((entries,), np.int64), np.full((entries,), eh, np.int64),
        serials, slen)
    meta = np.full((entries,), packing.pack_meta(0, eh, agg.base_hour),
                   np.uint32)
    ovf = agg._bulk_reinsert(keys, meta)
    if ovf:
        raise SystemExit(f"table too small: {ovf} overflow rows; "
                         "raise --table-bits")
    agg._table_fill = entries
    agg._device_written = True
    return agg, eh


def serial_bytes(j: int) -> bytes:
    return b"\x00" * 8 + int(j).to_bytes(8, "big")


# -- checkpoint-plane harness (CTMRCK02, round 22) ------------------------


def ckpt_churn(agg, eh: int, n: int, start: int) -> None:
    """Fold ``n`` fresh synthetic serials (same counter space as
    :func:`build_aggregator`, starting at ``start``) through the
    PRE-PARSED lane — the bulk-reinsert path build_aggregator uses
    bypasses fold-time dirty logging, which is fine for the base
    corpus but would make incremental-checkpoint churn invisible."""
    import numpy as np

    from ct_mapreduce_tpu.core import packing
    from ct_mapreduce_tpu.native.leafpack import Sidecar

    s = packing.MAX_SERIAL_BYTES
    serials = np.zeros((n, s), np.uint8)
    counters = np.arange(start, start + n, dtype=np.uint64)
    for i in range(8):
        serials[:, 15 - i] = ((counters >> np.uint64(8 * i))
                              & np.uint64(0xFF)).astype(np.uint8)
    zeros = np.zeros((n,), np.int32)
    # The fold path (unlike the bulk pre-fill) enforces the expiry
    # filter against the real clock: keep churn certs in the future
    # while staying inside the meta hour span of the base.
    nah = max(int(eh), agg._now_hour() + 1000)
    require(nah - agg.base_hour < packing.META_HOUR_SPAN,
            "churn expiry hour outside the fixture's meta span")
    sc = Sidecar(
        ok=np.ones((n,), np.uint8),
        serial_off=zeros, serial_len=np.full((n,), 16, np.int32),
        not_after_hour=np.full((n,), nah, np.int32),
        is_ca=np.zeros((n,), np.uint8),
        has_crldp=np.zeros((n,), np.uint8),
        cn_off=zeros, cn_len=zeros, issuer_off=zeros, issuer_len=zeros,
        spki_off=zeros, spki_len=zeros, crldp_off=zeros,
        crldp_len=zeros,
    )
    res = agg.ingest_preparsed(
        sc, np.zeros((n,), np.int32), np.ones((n,), bool),
        serials, np.full((n,), s, np.int32))
    require(int(res.was_unknown.sum()) == n,
            f"churn batch not fresh: {int(res.was_unknown.sum())}/{n} "
            "unknown (counter overlap with the base corpus?)")


def ckpt_state_digest(agg) -> str:
    """Canonical SHA-256 over the complete restorable aggregate state
    (sorted table rows, count, registry, counters, host/capture sets,
    content tokens) — the restore-parity oracle: a CTMRCK02 base +
    chain restore must digest identically to a ck01 full-save
    restore of the same state."""
    import hashlib

    import numpy as np

    keys, meta = agg._drain_table()
    rows = np.concatenate(
        [keys.astype(np.uint32),
         meta.astype(np.uint32).reshape(-1, 1)], axis=1)
    order = np.lexsort(rows.T[::-1])
    h = hashlib.sha256()
    h.update(rows[order].tobytes())
    h.update(str(int(agg._table_fill)).encode())
    h.update(agg.registry.to_json().encode())
    h.update(np.trim_zeros(agg.issuer_totals, "b").tobytes())
    h.update(np.trim_zeros(agg.verify_verified, "b").tobytes())
    h.update(np.trim_zeros(agg.verify_failed, "b").tobytes())
    for (i, e), ss in sorted(agg.host_serials.items()):
        h.update(f"h{i},{e};".encode())
        for sb in sorted(ss):
            h.update(sb)
    for i, urls in sorted(agg.crl_sets.items()):
        h.update(f"c{i};".encode())
        for u in sorted(urls):
            h.update(u.encode())
    for i, dns in sorted(agg.dn_sets.items()):
        h.update(f"d{i};".encode())
        for dn in sorted(dns):
            h.update(dn.encode())
    tokens = agg.capture_content_hashes()
    if tokens is not None:
        for (i, e), v in sorted(tokens.items()):
            h.update(f"t{i},{e},{v:032x};".encode())
    return h.hexdigest()


def make_oracle(agg, eh: int, entries: int, max_batch: int,
                max_delay_s: float, device: bool, replicas: int,
                cache_size: int, max_queue_lanes: int = 0):
    """A warmed MembershipOracle: snapshots pinned and the `contains`
    kernel compiled at every pow2 width the batcher can form BEFORE
    the timed window (compiles are per-shape and must not bill it).
    Probe keys sit outside [0, 2*entries) so warmup never aliases the
    sweep's probe domain through the cache."""
    from ct_mapreduce_tpu.serve.server import MembershipOracle

    oracle = MembershipOracle(
        agg, max_batch=max_batch, max_delay_s=max_delay_s,
        max_queue_lanes=max_queue_lanes or max(4 * max_batch, 1024),
        max_staleness_s=60.0, device=device, replicas=replicas,
        cache_size=cache_size if cache_size != 0 else -1)
    oracle.snapshots.warm()
    w = 16
    while w <= max_batch:
        oracle.query_raw([(0, eh, serial_bytes(2 * entries + k))
                          for k in range(w)])
        w *= 2
    return oracle


def probe_indices(rng, n: int, entries: int, zipf: float):
    """Probe mix over [0, 2*entries): uniform (zipf=0 — half present,
    half absent) or zipf-skewed ranks (a hot working set, the traffic
    shape the hot-serial cache exists for)."""
    import numpy as np

    if zipf <= 0:
        return rng.integers(0, 2 * entries, size=n)
    return np.minimum(rng.zipf(zipf, size=n) - 1, 2 * entries - 1)


def run_point(agg, eh: int, entries: int, max_batch: int,
              max_delay_s: float, threads: int, duration_s: float,
              device: bool, replicas: int = 1,
              cache_size: int = -1) -> dict:
    """Closed-loop sweep point: N client threads back-to-back (the
    round-10 shape; the arrival process throttles with the clients,
    so it can never show overload — see :func:`run_open_loop`)."""
    import threading

    import numpy as np

    from ct_mapreduce_tpu.serve.batcher import Overloaded
    from ct_mapreduce_tpu.telemetry import metrics as tmetrics

    sink = tmetrics.InMemSink()
    prev = tmetrics.get_sink()
    tmetrics.set_sink(sink)
    oracle = make_oracle(agg, eh, entries, max_batch, max_delay_s,
                         device, replicas, cache_size)
    lat: list[float] = []
    shed = [0]
    stop = time.perf_counter() + duration_s

    def client(seed: int) -> None:
        rng = np.random.default_rng(seed)
        while time.perf_counter() < stop:
            j = int(rng.integers(2 * entries))  # half present, half not
            t0 = time.perf_counter()
            try:
                res = oracle.query_raw([(0, eh, serial_bytes(j))])
            except Overloaded:
                shed.append(1)
                continue
            lat.append(time.perf_counter() - t0)
            require(res[0][0] == (j < entries), f"parity broke at {j}")

    ts = [threading.Thread(target=client, args=(s,))
          for s in range(threads)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = time.perf_counter() - t0
    oracle.close()
    tmetrics.set_sink(prev)
    snap = sink.snapshot()
    lanes = snap["counters"].get("serve.lanes", 0.0)
    batches = snap["counters"].get("serve.batches", 0.0)
    lat.sort()
    n = len(lat)
    return {
        "max_batch": max_batch,
        "max_delay_ms": round(max_delay_s * 1e3, 3),
        "qps": round(n / wall, 1),
        "p50_ms": round(lat[n // 2] * 1e3, 3) if n else None,
        "p99_ms": (round(lat[min(n - 1, int(0.99 * n))] * 1e3, 3)
                   if n else None),
        "mean_batch_lanes": round(lanes / batches, 2) if batches else 0.0,
        "shed": len(shed) - 1,
        "queries": n,
    }


def run_open_loop(agg, eh: int, entries: int, rate: float,
                  duration_s: float, arrival_batch: int, threads: int,
                  max_batch: int, max_delay_s: float, device: bool,
                  replicas: int, cache_size: int, zipf: float) -> dict:
    """One offered-rate point: arrivals of ``arrival_batch`` lanes
    land every ``arrival_batch / rate`` seconds on a fixed schedule;
    latency is measured from the SCHEDULED instant, so dispatcher
    backlog is latency (and past the admission bound, explicit shed)
    instead of hidden load-generator throttling."""
    import threading

    import numpy as np

    from ct_mapreduce_tpu.serve.batcher import Overloaded
    from ct_mapreduce_tpu.telemetry import metrics as tmetrics

    sink = tmetrics.InMemSink()
    prev = tmetrics.get_sink()
    tmetrics.set_sink(sink)
    oracle = make_oracle(agg, eh, entries, max_batch, max_delay_s,
                         device, replicas, cache_size,
                         max_queue_lanes=max(8 * max_batch, 4096))
    interval = arrival_batch / rate
    n_arrivals = max(1, int(duration_s / interval))
    rng = np.random.default_rng(42)
    sched = probe_indices(rng, n_arrivals * arrival_batch, entries,
                          zipf).reshape(n_arrivals, arrival_batch)
    lat: list[float] = []
    shed_lanes = [0]
    errors: list[str] = []
    next_ix = [0]
    ix_lock = threading.Lock()
    t_start = time.perf_counter() + 0.05  # let every worker reach the gate

    def worker() -> None:
        while True:
            with ix_lock:
                i = next_ix[0]
                next_ix[0] += 1
            if i >= n_arrivals:
                return
            t_i = t_start + i * interval
            now = time.perf_counter()
            if now < t_i:
                time.sleep(t_i - now)
            js = sched[i]
            items = [(0, eh, serial_bytes(int(j))) for j in js]
            try:
                res = oracle.query_raw(items)
            except Overloaded:
                shed_lanes.append(arrival_batch)
                continue
            lat.append(time.perf_counter() - t_i)  # GIL-atomic append
            for r, j in zip(res, js):
                if r[0] != (j < entries):
                    errors.append(f"parity broke at {j}")

    ts = [threading.Thread(target=worker) for _ in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = max(time.perf_counter() - t_start, 1e-9)
    oracle.close()
    tmetrics.set_sink(prev)
    if errors:
        raise ParityError(f"open-loop parity: {errors[:3]}")
    snap = sink.snapshot()
    counters = snap["counters"]
    lanes = counters.get("serve.lanes", 0.0)
    batches = counters.get("serve.batches", 0.0)
    hits = counters.get("serve.cache_hit", 0.0)
    misses = counters.get("serve.cache_miss", 0.0)
    done = len(lat) * arrival_batch
    offered = n_arrivals * arrival_batch
    lat.sort()
    n = len(lat)
    return {
        "offered_qps": round(rate, 1),
        "achieved_qps": round(done / wall, 1),
        "p50_ms": round(lat[n // 2] * 1e3, 3) if n else None,
        "p99_ms": (round(lat[min(n - 1, int(0.99 * n))] * 1e3, 3)
                   if n else None),
        "shed_frac": round(sum(shed_lanes) / offered, 4),
        "mean_batch_lanes": round(lanes / batches, 2) if batches else 0.0,
        "cache_hit_rate": (round(hits / (hits + misses), 4)
                           if hits + misses else 0.0),
        "lanes_done": done,
    }


# -- verify-lane harness (moved from tools/stagecost.py) ------------------


def verify_corpus(ops, n_uniq: int, n_keys: int):
    """Mixed valid/invalid signature corpus: ``n_uniq`` unique
    signatures tiled under ``n_keys`` distinct keys, 1/4 mutated, with
    the pure-python host verdicts as the parity reference."""
    import hashlib

    import numpy as np

    from ct_mapreduce_tpu.verify import host as vhost

    c = ops.curve
    nb = c.byte_len
    uniq, key_xy = [], []
    for i in range(n_uniq):
        seed = f"sc-{c.name}-{i % n_keys}"
        d = vhost.derive_scalar(seed, c)
        q = vhost._point_mul(c, d, (c.gx, c.gy))
        digest = hashlib.sha256(b"sc%d" % i).digest()
        k = vhost.derive_nonce(seed, digest, c)
        r, s_ = vhost.sign_ecdsa(c, digest, d, k)
        if i % 4 == 0:
            s_ ^= 1 << (i % 250)  # mutated lane
        uniq.append((digest, r, s_, q[0], q[1]))
        if i < n_keys:
            key_xy.append(q)
    href = [vhost.verify_ecdsa(c, dg, r, s_, x, y)
            for dg, r, s_, x, y in uniq]

    def bn(v):
        return np.frombuffer(
            (v % (1 << (8 * nb))).to_bytes(nb, "big"), np.uint8)

    rows = {
        "digest": np.stack([np.pad(
            np.frombuffer(u[0], np.uint8), (nb - 32, 0))
            for u in uniq]),
        "r": np.stack([bn(u[1]) for u in uniq]),
        "s": np.stack([bn(u[2]) for u in uniq]),
        "qx": np.stack([bn(u[3]) for u in uniq]),
        "qy": np.stack([bn(u[4]) for u in uniq]),
    }
    kidx = np.array([i % n_keys for i in range(n_uniq)], np.int32)
    return rows, href, kidx, key_xy


def verify_point(ops, width: int, window: int, corpus, reps: int = 3,
                 verbose: bool = True) -> TimedReps:
    """One (curve, width, window) verification point, bench
    methodology: window 0 is the legacy Jacobian ladder; window > 0
    measures the lane's steady state with G/Q tables device-resident
    before the timed region (100% qtable hits — the production regime
    under <100 log keys). Host-verdict parity asserted on every run,
    warmup included; table-build wall folds into ``compile_s``."""
    import jax as _jax
    import numpy as np

    from ct_mapreduce_tpu.ops import ecdsa

    rows, href, kidx, key_xy = corpus
    n_uniq = len(href)
    n_keys = len(key_xy)
    nl = ops.mod_p.nlimb
    tiles = -(-width // n_uniq)
    args = [np.tile(rows[k], (tiles, 1))[:width]
            for k in ("digest", "r", "s", "qx", "qy")]
    valid = np.ones((width,), bool)
    key_idx = np.tile(kidx, tiles)[:width]
    expect = (href * tiles)[:width]
    t_tab = 0.0
    if window == 0:
        fn = ecdsa.jacobian_jit(ops)
        call = lambda: fn(*args, valid)  # noqa: E731
    else:
        t0 = time.perf_counter()
        gtab, _ = ecdsa.fixed_base_table(ops, window)
        slots = max(ecdsa.MIN_QTABLE_SLOTS, n_keys)
        qtab = np.zeros(
            (slots, ops.nbits // window, 1 << window, 2, nl),
            np.uint32)
        for ki, (x, y) in enumerate(key_xy):
            qtab[ki] = ecdsa.point_table_cached(ops, window, x, y)[0]
        qtab_dev = _jax.device_put(qtab)
        t_tab = time.perf_counter() - t0
        if verbose:
            say(f"  verify {ops.name} B={width} w={window}: "
                f"tables {t_tab:.1f}s")
        fn = ecdsa.windowed_jit(ops)
        call = lambda: fn(*args, valid, key_idx,  # noqa: E731
                          gtab, qtab_dev)

    def check(out):
        require(np.asarray(out).tolist() == expect,
                f"verify {ops.name} B={width} w={window}: parity")

    tr = timed_reps(lambda: np.asarray(call()), reps=reps, check=check)
    tr.compile_s += t_tab  # table build is warmup-class wall too
    return tr


# -- staged-dispatch harness (moved from tools/stagecost.py) --------------


def staged_dispatch_corpus(b: int = 1024, n_chunks: int = 8,
                           pad_len: int = 1024):
    """Fixed total work for the K-curve: ``n_chunks`` chunks of ``b``
    walker lanes as host rows, plus the table capacity that holds
    them (returned as a dict the sweep function consumes)."""
    import numpy as np

    from ct_mapreduce_tpu.utils import syncerts

    tpl = syncerts.make_template(issuer_cn="Dispatch CA")
    datas, lens = syncerts.build_device_batches(tpl, n_chunks, b, pad_len)
    return {
        "b": b, "n_chunks": n_chunks,
        "datas": np.asarray(datas, np.uint8),
        "lens": np.asarray(lens, np.int32),
        "iidx": np.zeros((n_chunks, b), np.int32),
        "valid": np.ones((n_chunks, b), bool),
        "cap": 1 << max(14, (4 * n_chunks * b).bit_length()),
    }


def staged_dispatch_run(corpus: dict, k: int, mk_table=None):
    """One K-point of the staged-envelope curve: the REAL production
    shape per dispatch — host rows → one device_put → one
    ingest_step_staged call. Returns (wall_s, packed readbacks, table
    rows); callers assert byte parity of both against K=1."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ct_mapreduce_tpu.core import packing
    from ct_mapreduce_tpu.ops import buckettable, pipeline

    mk_table = mk_table or buckettable.make_table
    n_chunks, b = corpus["n_chunks"], corpus["b"]
    no_cn = np.zeros((0, 32), np.uint8)
    no_cn_lens = np.zeros((0, 2), np.int32)
    table = mk_table(corpus["cap"])
    packs = []
    t0 = time.perf_counter()
    for g in range(n_chunks // k):
        sl = slice(g * k, (g + 1) * k)
        data = jax.device_put(corpus["datas"][sl])
        table, out = pipeline.ingest_step_staged(
            table, data, corpus["lens"][sl], corpus["iidx"][sl],
            corpus["valid"][sl], jnp.int32(500_000),
            jnp.int32(packing.DEFAULT_BASE_HOUR), no_cn, no_cn_lens)
        packs.append(out.packed)
    packed = np.concatenate(
        [np.asarray(p) for p in packs], axis=0)  # sync point
    rows = np.asarray(table.rows)
    return time.perf_counter() - t0, packed, rows
