"""The knob inventory the autotuner sweeps — and the lint rule audits.

Every ``Knob`` spec in the tree must appear here in exactly one of two
tables, per section:

- ``SWEEPABLE[section][knob]`` — the declared value ladder the search
  driver walks (tune/measure.py providers consume these grids; the
  full-scale ladders are what a device campaign sweeps, the ``smoke``
  scale substitutes CPU-box-sized rungs — fewer points, and where the
  full rungs themselves are device-sized, smaller ones, e.g.
  ``verifyBatch`` [32]).
- ``EXCLUDED[section][knob]`` — a justification string (>= 15 chars,
  the ctmrlint.baseline discipline) for why the knob is NOT a
  performance scalar worth sweeping: identity, policy, or semantic
  choices that a measured curve must never overwrite.

The config-parity ctmrlint rule diffs this file against the
``_*_KNOBS`` declarations: a new Knob that lands in neither table
fails the lint gate, so the autotuner can never silently go stale
against the knob surface.

This module is import-light on purpose (no jax, no subsystem
imports): the lint rule parses it as AST, and the CLI/campaign import
it before any device shows up.
"""

from __future__ import annotations

# section -> (module holding the Knob tuple, attribute name). The show
# CLI imports these lazily to render the resolved ladder.
SECTIONS = {
    "staging": ("ct_mapreduce_tpu.ingest.sync", "_STAGING_KNOBS"),
    "serve": ("ct_mapreduce_tpu.serve.server", "_SERVE_KNOBS"),
    "verify": ("ct_mapreduce_tpu.verify.lane", "_VERIFY_KNOBS"),
    "fleet": ("ct_mapreduce_tpu.ingest.fleet", "_FLEET_KNOBS"),
    "filter": ("ct_mapreduce_tpu.filter", "_FILTER_KNOBS"),
    "distrib": ("ct_mapreduce_tpu.distrib", "_DISTRIB_KNOBS"),
    "ckpt": ("ct_mapreduce_tpu.agg.ckpt", "_CKPT_KNOBS"),
    "obs": ("ct_mapreduce_tpu.telemetry.fleetobs", "_OBS_KNOBS"),
    "audit": ("ct_mapreduce_tpu.audit", "_AUDIT_KNOBS"),
}

# Declared ladders, coarse-to-fine in the order the search walks them.
# Full-scale rungs target a device host; the smoke scale (measure.py)
# swaps in CPU-box-sized rungs for the same knobs.
SWEEPABLE = {
    "staging": {
        "chunksPerDispatch": [1, 2, 4, 8],
        "stagingDepth": [1, 2, 3, 4],
    },
    "serve": {
        "serveReplicas": [1, 2, 4],
    },
    "verify": {
        "verifyBatch": [256, 1024, 4096],
        "verifyPrecompWindow": [0, 2, 4, 8],
    },
    "fleet": {
        "numWorkers": [1, 2, 4],
    },
    "filter": {
        "filterStreamChunk": [0, 65536, 262144],
        "filterFusedLanes": [0, 1024, 4096],
        "filterCaptureSpillMB": [64, 256, 1024],
    },
    "distrib": {},
    "ckpt": {},
    "obs": {},
    "audit": {},
}

# Knobs the search must not touch, each with its justification.
EXCLUDED = {
    "staging": {},
    "serve": {
        "serveDevice": "capability toggle with automatic host "
                       "fallback, not a swept performance scalar",
        "serveCacheSize": "hit rate tracks the deployment's traffic "
                          "skew, not platform speed — operator policy",
    },
    "verify": {
        "verifySignatures": "workload on/off toggle: enables the "
                            "lane, does not tune it",
        "verifyLogKeys": "deployment key-list path — identity, not "
                         "performance",
        "verifyQTableSize": "LRU slots sized by the deployment's "
                            "log-key count, not by device speed",
    },
    "fleet": {
        "workerId": "worker identity within the fleet, never a "
                    "performance knob",
        "checkpointPeriod": "durability cadence is operator policy "
                            "(data-loss budget), not throughput",
        "coordinatorBackend": "fabric selection follows deployment "
                              "topology (redis vs jax.distributed)",
    },
    "filter": {
        "emitFilter": "workload on/off toggle: enables emission, "
                      "does not tune it",
        "filterPath": "artifact output location on the host "
                      "filesystem — not a performance scalar",
        "filterFpRate": "accuracy/size policy target; sweeping it "
                        "would trade correctness budget for speed",
        "filterCaptureSpillDir": "host filesystem location for the "
                                 "capture spill, not a perf scalar",
        "filterFormat": "wire-format semantic choice (fl01 compat vs "
                        "fl02), clients depend on it",
    },
    "distrib": {
        "distribHistory": "retention depth is storage/durability "
                          "policy, not a measured rate",
        "maxDeltaChain": "anchor cadence trades client wire bytes vs "
                         "server storage — policy, not platform",
    },
    "ckpt": {
        "checkpointMode": "wire-format semantic choice (ck01 oracle "
                          "vs ck02 incremental), not a swept scalar",
        "ckptMaxChain": "anchor cadence trades restore replay work "
                        "vs per-tick bytes — durability policy",
        "ckptSegmentBudgetMB": "dirty-log memory ceiling is an "
                               "operator host-RAM policy, not a "
                               "measured performance rate",
    },
    "obs": {
        "fleetMetrics": "observability on/off toggle: enables the "
                        "fan-in, does not tune a measured rate",
        "sloMaxIngestLag": "SLO threshold is an operator service "
                           "objective, never a swept performance "
                           "scalar",
        "sloMaxCheckpointAge": "SLO threshold encodes the data-loss "
                               "budget — operator policy, not speed",
        "sloMaxFilterLag": "SLO threshold is a freshness objective "
                           "for filter consumers, not a measured rate",
        "sloMaxServeP99Ms": "SLO threshold is the latency objective "
                            "being judged — sweeping it is circular",
    },
    "audit": {
        "auditLogList": "trust-anchor list path — identity, never a "
                        "performance scalar",
        "auditQuarantineDir": "divergence spool location on the host "
                              "filesystem, not a perf scalar",
    },
}


def audit() -> list:
    """Cross-check the registry against the live Knob declarations
    (the runtime twin of the lint rule — tests call this; the lint
    rule re-derives the same diff from AST without importing jax).
    Returns a list of human-readable problems, empty when clean."""
    import importlib

    problems = []
    for section, (mod_name, attr) in SECTIONS.items():
        try:
            mod = importlib.import_module(mod_name)
            knobs = getattr(mod, attr)
        except Exception as err:  # pragma: no cover - import breakage
            problems.append(f"{section}: cannot load {mod_name}.{attr}"
                            f": {err}")
            continue
        swept = SWEEPABLE.get(section, {})
        excl = EXCLUDED.get(section, {})
        for knob in knobs:
            hit_s, hit_e = knob.name in swept, knob.name in excl
            if hit_s and hit_e:
                problems.append(f"{section}.{knob.name}: both "
                                "sweepable and excluded")
            elif not (hit_s or hit_e):
                problems.append(f"{section}.{knob.name}: in neither "
                                "SWEEPABLE nor EXCLUDED")
        names = {k.name for k in knobs}
        for name in list(swept) + list(excl):
            if name not in names:
                problems.append(f"{section}.{name}: registered but no "
                                "such Knob is declared")
        for name, ladder in swept.items():
            if not isinstance(ladder, list) or not ladder:
                problems.append(f"{section}.{name}: empty ladder")
        for name, why in excl.items():
            if not isinstance(why, str) or len(why) < 15:
                problems.append(f"{section}.{name}: exclusion needs a "
                                ">=15 char justification")
    return problems
