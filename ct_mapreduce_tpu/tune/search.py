"""Search driver: coordinate descent + successive halving on a knob grid.

The knob spaces here are small (a handful of axes, each a short
declared ladder — tune/registry.py), discrete, and expensive to probe
(a point is a real device measurement with compile warmup). The shape
that fits is the one XLA's own kernel autotuner uses: sweep one axis
at a time from the current best (coordinate descent — the axes are
close to separable: dispatch toll vs K, batcher occupancy vs
replicas), and spend reps unevenly (successive halving — every
candidate gets a cheap low-rep probe, only the surviving half gets the
confirmatory high-rep evaluation that decides).

Deterministic by construction: the only randomness is the per-sweep
axis order drawn from ``random.Random(seed)``, evaluation results are
cached by point, and nothing here reads a clock except to enforce the
wall budget (budgets change *when the search stops*, never *what a
given evaluation sequence returns*). No wall-clock value ever reaches
emitted profile bytes — measured walls live only in provenance.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ct_mapreduce_tpu.telemetry import metrics


@dataclass
class EvalResult:
    """One evaluation of one point: the metric's mean over ``reps``
    runs, its spread, the wall it cost, and whether the point is
    feasible at all (e.g. serve p99 blew the ingest-concurrent bound —
    infeasible points are measured but can never win)."""

    mean: float
    std: float = 0.0
    reps: int = 1
    wall_s: float = 0.0
    feasible: bool = True


@dataclass
class SearchResult:
    best: dict
    best_value: float  # the metric's mean at ``best`` (raw, unsigned)
    # Every evaluate() call in order: (point, reps asked, EvalResult).
    evaluations: list = field(default_factory=list)
    evals_used: int = 0  # rep-weighted cost spent
    wall_s: float = 0.0
    budget_exhausted: bool = False
    # knob -> [[value, mean], ...]: the measured 1-D slice through the
    # final best point (the provenance curve the profile records).
    curves: dict = field(default_factory=dict)


def _key(grid: dict, point: dict) -> tuple:
    return tuple(point[k] for k in grid)


def coordinate_descent(
        grid: dict, evaluate: Callable[[dict, int], EvalResult], *,
        maximize: bool = True, seed: int = 0,
        budget_evals: int = 0, budget_wall_s: float = 0.0,
        reps: tuple = (1, 3), sweeps: int = 3,
        start: Optional[dict] = None,
        clock: Callable[[], float] = time.perf_counter) -> SearchResult:
    """Find the best point of ``grid`` (knob -> declared value ladder)
    under ``evaluate(point, reps) -> EvalResult``.

    ``budget_evals`` bounds the rep-weighted evaluation count and
    ``budget_wall_s`` the harness wall (0 = unbounded); when either
    trips, the best point seen so far returns with
    ``budget_exhausted=True``. ``reps = (low, high)`` is the
    successive-halving split: every candidate on an axis gets a
    ``low``-rep probe, the top half get the ``high``-rep confirmation.
    """
    if not grid or any(not v for v in grid.values()):
        raise ValueError("grid must map every knob to a non-empty ladder")
    rng = random.Random(seed)
    reps_lo, reps_hi = int(reps[0]), int(reps[-1])
    sign = 1.0 if maximize else -1.0
    t_start = clock()
    res = SearchResult(best={}, best_value=float("-inf"))
    # point key -> (reps evaluated at, EvalResult); higher reps replace.
    cache: dict[tuple, tuple[int, EvalResult]] = {}

    def over_budget() -> bool:
        if budget_evals and res.evals_used >= budget_evals:
            return True
        if budget_wall_s and clock() - t_start >= budget_wall_s:
            return True
        return False

    def score(er: EvalResult) -> float:
        return sign * er.mean if er.feasible else float("-inf")

    def probe(point: dict, n: int) -> Optional[EvalResult]:
        got = cache.get(_key(grid, point))
        if got is not None and got[0] >= n:
            return got[1]
        if over_budget():
            return None
        er = evaluate(dict(point), n)
        cache[_key(grid, point)] = (n, er)
        res.evaluations.append((dict(point), n, er))
        res.evals_used += n
        metrics.incr_counter("tune", "evaluations")
        metrics.add_sample("tune", "eval_s", value=er.wall_s)
        return er

    best_score = float("-inf")

    def consider(point: dict, er: EvalResult) -> float:
        nonlocal best_score
        s = score(er)
        if s > best_score:
            best_score = s
            res.best, res.best_value = dict(point), er.mean
        return s

    cur = dict(start) if start else {k: v[0] for k, v in grid.items()}
    for k, ladder in grid.items():
        if cur.get(k) not in ladder:
            raise ValueError(f"start[{k}]={cur.get(k)!r} not on its "
                             f"ladder {ladder}")
    er = probe(cur, reps_hi)
    if er is not None:
        consider(cur, er)

    for _ in range(max(1, int(sweeps))):
        moved = False
        axes = list(grid)
        rng.shuffle(axes)
        for axis in axes:
            # Low-rep probe of every rung on this axis...
            scored = []
            for v in grid[axis]:
                cand = dict(cur, **{axis: v})
                er = probe(cand, reps_lo)
                if er is None:
                    res.budget_exhausted = True
                    break
                scored.append((score(er), v))
            if res.budget_exhausted:
                break
            # ...then the surviving half gets the high-rep confirm.
            scored.sort(key=lambda sv: sv[0], reverse=True)
            keep = scored[:max(1, -(-len(scored) // 2))]
            best_v, best_s = cur[axis], float("-inf")
            for _, v in keep:
                cand = dict(cur, **{axis: v})
                er = probe(cand, reps_hi)
                if er is None:
                    res.budget_exhausted = True
                    break
                s = consider(cand, er)
                if s > best_s:
                    best_v, best_s = v, s
            if res.budget_exhausted:
                break
            if best_s > float("-inf") and best_v != cur[axis]:
                cur[axis] = best_v
                moved = True
        if res.budget_exhausted or not moved:
            break

    if not res.best:  # first probe already over budget
        res.best, res.best_value = dict(cur), float("nan")
    # Provenance curves: the measured 1-D slice through the best point
    # along each axis (whatever rungs the search actually probed).
    for axis, ladder in grid.items():
        curve = []
        for v in ladder:
            got = cache.get(_key(grid, dict(res.best, **{axis: v})))
            if got is not None:
                curve.append([v, got[1].mean])
        res.curves[axis] = curve
    res.wall_s = clock() - t_start
    if res.best_value == res.best_value:  # not NaN
        metrics.set_gauge("tune", "best_value", value=res.best_value)
    return res
