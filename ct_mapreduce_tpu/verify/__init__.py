"""Signature verification: the auditing workload (round 13).

- :mod:`ct_mapreduce_tpu.verify.host` — the pure-python reference
  verifier (generic short-Weierstrass ECDSA + RSA PKCS#1 v1.5). The
  ground truth every device verdict is bit-identical to, and the
  fallback lane for signatures the device kernel doesn't cover.
- :mod:`ct_mapreduce_tpu.verify.sct` — the embedded-SCT wire format:
  extension scan, TLS SCT-list parsing, the reproduction's signed-
  payload convention, fixture signers, and DER surgery to embed SCTs
  into any certificate.
- :mod:`ct_mapreduce_tpu.verify.lane` — the ingest-side verification
  lane: log-key registry, device-batch staging with async dispatch,
  host-fallback replay, per-issuer verified/failed fold into the
  aggregator.
"""
