"""Embedded-SCT wire format: extraction, digest convention, fixtures.

An embedded SCT lives in certificate extension OID
1.3.6.1.4.1.11129.2.4.2 as an OCTET STRING holding a TLS-encoded
``SignedCertificateTimestampList`` (RFC 6962 §3.3): per SCT —
version(1) ‖ log_id(32) ‖ timestamp(8, ms) ‖ extensions(2+n) ‖
hash_alg(1) ‖ sig_alg(1) ‖ sig_len(2) ‖ signature. For ECDSA the
signature bytes are a DER ``ECDSA-Sig-Value`` (SEQUENCE of two
INTEGERs).

**Signed-payload convention (RFC 6962 §3.2, round 24).** An embedded
SCT signs the *reconstructed precert TBS*: the TBSCertificate with the
SCT-list extension (and any poison extension,
1.3.6.1.4.1.11129.2.4.3) removed and every enclosing DER length
re-encoded minimally, wrapped as a ``precert_entry``:

    version(0x00) ‖ sig_type(0x00) ‖ timestamp(8 BE) ‖
    entry_type(0x0001) ‖ issuer_key_hash(32) ‖
    len3(tbs') ‖ tbs' ‖ ext_len(2 BE) ‖ ext_bytes

where ``tbs'`` = :func:`reconstruct_precert_tbs` and
``issuer_key_hash`` = SHA-256 of the issuing certificate's SPKI DER
(:func:`issuer_key_hash_of`; all-zero when the lane carries no issuer
chain — such lanes can never verify against a real log key, matching
RFC semantics). The digest is still independent of the signature bytes
(they live inside the removed extension), which is what lets
:func:`attach_sct` sign-then-patch. This REPLACES the pre-round-24
byte-splice convention (PR 8's documented limit): real embedded SCTs
from production logs now verify against production log keys
(``audit/loglist.py``).

``extract_scts_np`` is the pure-python mirror of the native
``ctmr_extract_scts`` pass (ctmr_native.cpp) — bit-identical outputs,
pinned by tests/test_ecdsa.py — and the fallback when the native
library is unavailable (the PR-1 degradation contract).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

import numpy as np

from ct_mapreduce_tpu.verify import host

# OID 1.3.6.1.4.1.11129.2.4.2 content bytes.
SCT_OID = bytes.fromhex("2b06010401d679020402")
# OID 1.3.6.1.4.1.11129.2.4.3 — the precert poison extension
# (RFC 6962 §3.1); stripped alongside the SCT list during TBS
# reconstruction so a precert and its final cert sign identically.
POISON_OID = bytes.fromhex("2b06010401d679020403")

# issuer_key_hash for lanes with no issuer chain.
ZERO_IKH = bytes(32)

# Lane status codes (keep in sync with ctmr_native.cpp).
SCT_NONE = 0  # no (parseable) SCT extension on the lane
SCT_OK = 1  # P-256-shaped SCT: digest/r/s/log_id ready for the device
SCT_FALLBACK = 2  # SCT present but not device-decidable (non-ECDSA
# algorithm bytes, oversized integers, malformed TLS/DER innards):
# replay through the pure-python host verifier

HASH_SHA256 = 4
SIG_ECDSA = 3
SIG_RSA = 1


def _tlv(der: bytes, off: int, end: int):
    """One DER TLV header at ``off``: (tag, content_off, content_len)
    or None when malformed/truncated. Matches the native scanner's
    acceptance exactly (definite lengths up to 4 bytes)."""
    if off + 2 > end:
        return None
    tag = der[off]
    first = der[off + 1]
    off += 2
    if first < 0x80:
        length = first
    else:
        nb = first & 0x7F
        if nb == 0 or nb > 4 or off + nb > end:
            return None
        length = int.from_bytes(der[off : off + nb], "big")
        off += nb
    if off + length > end:
        return None
    return tag, off, length


def find_sct_extension(der: bytes):
    """Locate the SCT extension: returns ``(tlv_off, tlv_end, val_off,
    val_end)`` of the extension TLV and its extnValue OCTET STRING
    content, or None. Plain TLV walk (version, serial, sigalg, issuer,
    validity, subject, SPKI, optional [1]/[2], then [3] extensions)."""
    n = len(der)
    t = _tlv(der, 0, n)
    if t is None or t[0] != 0x30:
        return None
    _, cert_off, cert_len = t
    t = _tlv(der, cert_off, cert_off + cert_len)
    if t is None or t[0] != 0x30:
        return None
    _, tbs_off, tbs_len = t
    end = tbs_off + tbs_len
    off = tbs_off
    t = _tlv(der, off, end)
    if t is None:
        return None
    if t[0] == 0xA0:  # explicit [0] version
        off = t[1] + t[2]
    for _ in range(6):  # serial, sigalg, issuer, validity, subject, SPKI
        t = _tlv(der, off, end)
        if t is None:
            return None
        off = t[1] + t[2]
    while off < end:
        t = _tlv(der, off, end)
        if t is None:
            return None
        tag, c_off, c_len = t
        if tag == 0xA3:
            break
        off = c_off + c_len  # [1]/[2] issuer/subjectUniqueID
    else:
        return None
    t = _tlv(der, c_off, c_off + c_len)
    if t is None or t[0] != 0x30:
        return None
    _, seq_off, seq_len = t
    off, end = seq_off, seq_off + seq_len
    while off < end:
        ext = _tlv(der, off, end)
        if ext is None or ext[0] != 0x30:
            return None
        _, e_off, e_len = ext
        ext_end = e_off + e_len
        oid = _tlv(der, e_off, ext_end)
        if oid is None or oid[0] != 0x06:
            return None
        is_sct = der[oid[1] : oid[1] + oid[2]] == SCT_OID
        p = oid[1] + oid[2]
        t2 = _tlv(der, p, ext_end)
        if t2 is not None and t2[0] == 0x01:  # critical BOOLEAN
            p = t2[1] + t2[2]
            t2 = _tlv(der, p, ext_end)
        if t2 is None or t2[0] != 0x04:
            return None
        if is_sct:
            return off, ext_end, t2[1], t2[1] + t2[2]
        off = ext_end
    return None


def find_spki(der: bytes):
    """Locate the subjectPublicKeyInfo TLV: (tlv_off, tlv_end) of the
    full SPKI SEQUENCE (header included), or None. Same acceptance as
    :func:`find_sct_extension`'s walk — SPKI is the sixth field after
    the optional [0] version."""
    n = len(der)
    t = _tlv(der, 0, n)
    if t is None or t[0] != 0x30:
        return None
    t = _tlv(der, t[1], t[1] + t[2])
    if t is None or t[0] != 0x30:
        return None
    _, tbs_off, tbs_len = t
    end = tbs_off + tbs_len
    off = tbs_off
    t = _tlv(der, off, end)
    if t is None:
        return None
    if t[0] == 0xA0:  # explicit [0] version
        off = t[1] + t[2]
    for _ in range(5):  # serial, sigalg, issuer, validity, subject
        t = _tlv(der, off, end)
        if t is None:
            return None
        off = t[1] + t[2]
    t = _tlv(der, off, end)
    if t is None or t[0] != 0x30:
        return None
    return off, t[1] + t[2]


def issuer_key_hash_of(issuer_der: bytes) -> bytes:
    """RFC 6962 issuer_key_hash: SHA-256 over the issuing cert's SPKI
    DER (header included). All-zero when the issuer doesn't parse —
    the lane then carries a hash no real log signed, so it fails
    verification instead of silently passing."""
    win = find_spki(issuer_der)
    if win is None:
        return ZERO_IKH
    return hashlib.sha256(issuer_der[win[0]:win[1]]).digest()


def reconstruct_precert_tbs(der: bytes):
    """RFC 6962 §3.2 TBS reconstruction: the certificate's
    TBSCertificate with every SCT-list and poison extension removed
    and the enclosing lengths ([3] → extensions SEQUENCE → TBS)
    re-encoded minimally. When stripping empties the extensions list,
    the [3] element is omitted entirely. Returns the re-encoded TBS
    bytes (header included), or None when the certificate doesn't
    parse to the extractor's acceptance.

    The native scanner (ctmr_native.cpp ``sctext::digest_lane``)
    streams exactly these bytes into SHA-256 without materializing the
    buffer; parity is pinned by the KAT + mutation fuzz in
    tests/test_audit.py."""
    n = len(der)
    t = _tlv(der, 0, n)
    if t is None or t[0] != 0x30:
        return None
    t = _tlv(der, t[1], t[1] + t[2])
    if t is None or t[0] != 0x30:
        return None
    _, tbs_off, tbs_len = t
    tbs_end = tbs_off + tbs_len
    off = tbs_off
    t = _tlv(der, off, tbs_end)
    if t is None:
        return None
    if t[0] == 0xA0:
        off = t[1] + t[2]
    for _ in range(6):  # serial, sigalg, issuer, validity, subj, SPKI
        t = _tlv(der, off, tbs_end)
        if t is None:
            return None
        off = t[1] + t[2]
    # Trailing elements: [1]/[2] unique IDs pass through; [3] is the
    # extensions element to rebuild.
    a3_off = None
    while off < tbs_end:
        t = _tlv(der, off, tbs_end)
        if t is None:
            return None
        if t[0] == 0xA3:
            a3_off = off
            a3_end = t[1] + t[2]
            seq = _tlv(der, t[1], a3_end)
            if seq is None or seq[0] != 0x30:
                return None
            seq_off, seq_len = seq[1], seq[2]
            break
        off = t[1] + t[2]
    if a3_off is None:
        # No extensions: the reconstruction is the TBS content as-is
        # (re-wrapped so a non-minimal original length normalizes).
        return _wrap_tlv(0x30, der[tbs_off:tbs_end])
    kept = bytearray()
    p, p_end = seq_off, seq_off + seq_len
    while p < p_end:
        ext = _tlv(der, p, p_end)
        if ext is None or ext[0] != 0x30:
            return None
        ext_end = ext[1] + ext[2]
        oid = _tlv(der, ext[1], ext_end)
        if oid is None or oid[0] != 0x06:
            return None
        o = der[oid[1]:oid[1] + oid[2]]
        if o != SCT_OID and o != POISON_OID:
            kept += der[p:ext_end]
        p = ext_end
    new_exts = b""
    if kept:
        new_exts = _wrap_tlv(0xA3, _wrap_tlv(0x30, bytes(kept)))
    content = der[tbs_off:a3_off] + new_exts + der[a3_end:tbs_end]
    return _wrap_tlv(0x30, content)


@dataclass
class ParsedSct:
    """First SCT of a lane's list, as far as the wire parse got."""

    log_id: bytes
    timestamp_ms: int
    extensions: bytes
    hash_alg: int
    sig_alg: int
    signature: bytes
    version: int


def parse_sct_list(blob: bytes):
    """First SCT of a serialized SCT list, or None when malformed."""
    if len(blob) < 2:
        return None
    total = int.from_bytes(blob[0:2], "big")
    if total + 2 > len(blob) or total < 2:
        return None
    n0 = int.from_bytes(blob[2:4], "big")
    p = 4
    if p + n0 > len(blob) or n0 < 47:  # 1+32+8+2+1+1+2 header minimum
        return None
    end = p + n0
    version = blob[p]
    log_id = blob[p + 1 : p + 33]
    ts = int.from_bytes(blob[p + 33 : p + 41], "big")
    ext_len = int.from_bytes(blob[p + 41 : p + 43], "big")
    q = p + 43
    if q + ext_len + 4 > end:
        return None
    ext = blob[q : q + ext_len]
    q += ext_len
    hash_alg, sig_alg = blob[q], blob[q + 1]
    sig_len = int.from_bytes(blob[q + 2 : q + 4], "big")
    q += 4
    if q + sig_len != end:
        return None
    return ParsedSct(
        log_id=log_id, timestamp_ms=ts, extensions=ext,
        hash_alg=hash_alg, sig_alg=sig_alg, signature=blob[q:end],
        version=version,
    )


def parse_ecdsa_sig(sig: bytes, max_bytes: int = 32):
    """DER ECDSA-Sig-Value → (r, s) ints with both values <
    2^(8·max_bytes), or None. Accepts non-minimal INTEGER paddings up
    to one leading zero byte past max_bytes (the fixed-width fixture
    encoding); anything wider routes to the host fallback."""
    n = len(sig)
    t = _tlv(sig, 0, n)
    if t is None or t[0] != 0x30 or t[1] + t[2] != n:
        return None
    off, end = t[1], t[1] + t[2]
    vals = []
    for _ in range(2):
        t = _tlv(sig, off, end)
        if t is None or t[0] != 0x02 or t[2] < 1:
            return None
        content = sig[t[1] : t[1] + t[2]]
        stripped = content.lstrip(b"\x00") or b"\x00"
        if len(stripped) > max_bytes:
            return None
        vals.append(int.from_bytes(stripped, "big"))
        off = t[1] + t[2]
    if off != end:
        return None
    return vals[0], vals[1]


def sct_digest(der: bytes, tlv_off: int, tlv_end: int,
               timestamp_ms: int, extensions: bytes = b"",
               issuer_key_hash: bytes = ZERO_IKH):
    """The RFC 6962 §3.2 SHA-256 signing digest for one lane's
    embedded SCT (precert_entry over the reconstructed TBS), or None
    when the certificate doesn't reconstruct. ``tlv_off``/``tlv_end``
    are accepted for signature continuity with the pre-round-24
    convention (the reconstruction re-finds and strips every SCT/
    poison extension itself)."""
    del tlv_off, tlv_end
    tbs = reconstruct_precert_tbs(der)
    if tbs is None:
        return None
    payload = (
        b"\x00\x00"
        + timestamp_ms.to_bytes(8, "big")
        + b"\x00\x01"
        + issuer_key_hash
        + len(tbs).to_bytes(3, "big")
        + tbs
        + len(extensions).to_bytes(2, "big")
        + extensions
    )
    return hashlib.sha256(payload).digest()


@dataclass
class SctBatch:
    """Per-lane SCT extraction output for a packed row batch — the
    verification analog of :class:`~ct_mapreduce_tpu.native.leafpack.
    Sidecar`. All arrays length n."""

    ok: np.ndarray  # uint8[n] — SCT_NONE / SCT_OK / SCT_FALLBACK
    digest: np.ndarray  # uint8[n, 32] — convention digest (ok != 0)
    log_id: np.ndarray  # uint8[n, 32]
    timestamp_ms: np.ndarray  # int64[n]
    r: np.ndarray  # uint8[n, 32] big-endian (ok == SCT_OK)
    s: np.ndarray  # uint8[n, 32]
    hash_alg: np.ndarray  # uint8[n]
    sig_alg: np.ndarray  # uint8[n]

    @classmethod
    def empty(cls, n: int) -> "SctBatch":
        return cls(
            ok=np.zeros((n,), np.uint8),
            digest=np.zeros((n, 32), np.uint8),
            log_id=np.zeros((n, 32), np.uint8),
            timestamp_ms=np.zeros((n,), np.int64),
            r=np.zeros((n, 32), np.uint8),
            s=np.zeros((n, 32), np.uint8),
            hash_alg=np.zeros((n,), np.uint8),
            sig_alg=np.zeros((n,), np.uint8),
        )


def extract_sct_lane(der: bytes, issuer_key_hash: bytes = ZERO_IKH):
    """One lane: (status, ParsedSct | None, digest | None, r, s).

    The native scanner implements exactly this classification; keep
    the two in lockstep (parity pinned by the extraction fuzz)."""
    win = find_sct_extension(der)
    if win is None:
        return SCT_NONE, None, None, 0, 0
    tlv_off, tlv_end, v_off, v_end = win
    sct = parse_sct_list(der[v_off:v_end])
    if sct is None:
        return SCT_NONE, None, None, 0, 0
    digest = sct_digest(der, tlv_off, tlv_end, sct.timestamp_ms,
                        sct.extensions, issuer_key_hash)
    if digest is None:  # pragma: no cover - find succeeded, so walk does
        return SCT_NONE, None, None, 0, 0
    if (sct.version != 0 or sct.hash_alg != HASH_SHA256
            or sct.sig_alg != SIG_ECDSA):
        return SCT_FALLBACK, sct, digest, 0, 0
    rs = parse_ecdsa_sig(sct.signature, 32)
    if rs is None:
        return SCT_FALLBACK, sct, digest, 0, 0
    return SCT_OK, sct, digest, rs[0], rs[1]


def extract_scts_np(data: np.ndarray, length: np.ndarray,
                    issuer_key_hash=None) -> SctBatch:
    """Python extraction over packed rows uint8[n, pad] + int32[n]
    lengths — the no-native fallback (and the native pass's parity
    reference). ``issuer_key_hash``: uint8[n, 32] per-lane issuer key
    hashes (None → all-zero: no issuer chain)."""
    n = int(data.shape[0])
    out = SctBatch.empty(n)
    for i in range(n):
        ln = int(length[i])
        if ln <= 0:
            continue
        der = data[i, :ln].tobytes()
        ikh = (ZERO_IKH if issuer_key_hash is None
               else bytes(issuer_key_hash[i]))
        status, sct, digest, r, s = extract_sct_lane(der, ikh)
        out.ok[i] = status
        if sct is None:
            continue
        out.digest[i] = np.frombuffer(digest, np.uint8)
        out.log_id[i] = np.frombuffer(sct.log_id, np.uint8)
        out.timestamp_ms[i] = sct.timestamp_ms
        out.hash_alg[i] = sct.hash_alg
        out.sig_alg[i] = sct.sig_alg
        if status == SCT_OK:
            out.r[i] = np.frombuffer(r.to_bytes(32, "big"), np.uint8)
            out.s[i] = np.frombuffer(s.to_bytes(32, "big"), np.uint8)
    return out


# -- fixture signers + DER surgery --------------------------------------

# Deterministic 1023-bit RSA fixture key (test-only; generated once,
# seeded miller-rabin — no host can "regenerate" it wrong).
RSA_FIXTURE_N = int(
    "663b77f7b119250800268282b0a06532bf8a474366749630f66def6cb969f15b"
    "049e0e1ea899adbed610df45822154d8e9994b844ea259a87b7a0dcf1f3d78e3"
    "2bc898d63d6f52726894d6c2cae7f1c7223bd0eac13d66b6c8c7a39961d1978b"
    "d5504aaa60275d378e265fa82f466357f4ffdddde8c9929a53958ad88f0b3e6b",
    16,
)
RSA_FIXTURE_E = 65537
RSA_FIXTURE_D = int(
    "1b2fad537d1106bbfdee3fbea961be07a4d00ceb6b8f8d712fd7445851664efc"
    "b9599ebfa06e5db9e60b4e94996a6bb9d34524c3e6755e0a63ebad486b3259b7"
    "18dff82e62c7f9385643845f8594a7269f9e32cc517592b6a82f3315b8f4dd03"
    "3587c3ecfff7a4ea32683c9ca456425765c17c450c3a581f7dd87ff0be701c81",
    16,
)


def _fixed_ecdsa_der(r: int, s: int, width: int) -> bytes:
    """Fixed-length ECDSA-Sig-Value: both INTEGERs padded to
    ``width + 1`` content bytes (leading 0x00) so the signature length
    — and with it the SCT extension length, and with *it* the signed
    splice — is known before signing."""
    def part(v: int) -> bytes:
        body = b"\x00" + v.to_bytes(width, "big")
        return bytes([0x02, len(body)]) + body

    body = part(r) + part(s)
    return bytes([0x30, len(body)]) + body


class EcSctSigner:
    """Deterministic fixture log key on a named curve. P-256 keys are
    device-decidable; anything else routes to the host fallback."""

    def __init__(self, seed: str, curve: host.Curve = host.P256):
        self.seed = seed
        self.curve = curve
        self.d = host.derive_scalar(seed, curve)
        self.q = host._point_mul(curve, self.d, (curve.gx, curve.gy))
        w = curve.byte_len
        self.log_id = hashlib.sha256(
            b"ctmr-log-v1:" + curve.name.encode() + b":"
            + self.q[0].to_bytes(w, "big") + self.q[1].to_bytes(w, "big")
        ).digest()
        self.hash_alg = HASH_SHA256
        self.sig_alg = SIG_ECDSA
        self.sig_len = 2 + 2 * (2 + curve.byte_len + 1)

    def sign(self, digest: bytes) -> bytes:
        k = host.derive_nonce(self.seed, digest, self.curve)
        r, s = host.sign_ecdsa(self.curve, digest, self.d, k)
        return _fixed_ecdsa_der(r, s, self.curve.byte_len)

    def key_entry(self) -> dict:
        return {
            "log_id": self.log_id.hex(),
            "alg": self.curve.name,
            "x": hex(self.q[0]),
            "y": hex(self.q[1]),
        }


class RsaSctSigner:
    """RSA PKCS#1-v1.5 fixture log key — always a host-fallback lane."""

    def __init__(self, n: int = RSA_FIXTURE_N, e: int = RSA_FIXTURE_E,
                 d: int = RSA_FIXTURE_D):
        self.n, self.e, self.d = n, e, d
        k = (n.bit_length() + 7) // 8
        self.log_id = hashlib.sha256(
            b"ctmr-log-v1:rsa:" + n.to_bytes(k, "big")
            + e.to_bytes(4, "big")
        ).digest()
        self.hash_alg = HASH_SHA256
        self.sig_alg = SIG_RSA
        self.sig_len = k

    def sign(self, digest: bytes) -> bytes:
        return host.sign_rsa_pkcs1_sha256(digest, self.n, self.d)

    def key_entry(self) -> dict:
        return {
            "log_id": self.log_id.hex(),
            "alg": "rsa",
            "n": hex(self.n),
            "e": hex(self.e),
        }


def _wrap_tlv(tag: int, content: bytes) -> bytes:
    n = len(content)
    if n < 0x80:
        return bytes([tag, n]) + content
    if n < 0x100:
        return bytes([tag, 0x81, n]) + content
    if n < 0x10000:
        return bytes([tag, 0x82, n >> 8, n & 0xFF]) + content
    return bytes([tag, 0x83, n >> 16, (n >> 8) & 0xFF, n & 0xFF]) + content


def build_sct_list(log_id: bytes, timestamp_ms: int, hash_alg: int,
                   sig_alg: int, signature: bytes,
                   extensions: bytes = b"") -> bytes:
    """Serialize a one-SCT SignedCertificateTimestampList."""
    sct = (
        b"\x00" + log_id + timestamp_ms.to_bytes(8, "big")
        + len(extensions).to_bytes(2, "big") + extensions
        + bytes([hash_alg, sig_alg])
        + len(signature).to_bytes(2, "big") + signature
    )
    body = len(sct).to_bytes(2, "big") + sct
    return len(body).to_bytes(2, "big") + body


def attach_sct(der: bytes, signer, timestamp_ms: int,
               extensions: bytes = b"",
               corrupt_signature: bool = False,
               issuer_key_hash: bytes = ZERO_IKH,
               issuer_der: bytes = b"") -> bytes:
    """Embed a signed SCT into an existing certificate by DER surgery.

    The SCT extension is appended as the LAST extension (creating the
    [3] list if absent), with a zeroed fixed-length signature; the
    RFC 6962 digest is computed over the reconstructed TBS (which
    excludes the whole extension, hence the signature), the signer
    signs it, and the signature bytes are patched in place.
    ``corrupt_signature`` flips a bit post-signing (failing fixture).
    The signed issuer_key_hash comes from ``issuer_der`` (the issuing
    cert, hashed via :func:`issuer_key_hash_of`) or raw
    ``issuer_key_hash``; default all-zero matches lanes ingested
    without an issuer chain.
    """
    if issuer_der:
        issuer_key_hash = issuer_key_hash_of(issuer_der)
    n = len(der)
    t = _tlv(der, 0, n)
    if t is None or t[0] != 0x30:
        raise ValueError("not a certificate SEQUENCE")
    _, cert_off, cert_len = t
    tbs = _tlv(der, cert_off, cert_off + cert_len)
    if tbs is None or tbs[0] != 0x30:
        raise ValueError("no TBSCertificate")
    tbs_off, tbs_len = tbs[1], tbs[2]
    tbs_end = tbs_off + tbs_len
    rest = der[tbs_end:]  # signatureAlgorithm + signatureValue TLVs
    tbs_content = der[tbs_off:tbs_end]

    placeholder = bytes(signer.sig_len)
    ext_value = build_sct_list(
        signer.log_id, timestamp_ms, signer.hash_alg, signer.sig_alg,
        placeholder, extensions,
    )
    sct_ext = _wrap_tlv(
        0x30, _wrap_tlv(0x06, SCT_OID) + _wrap_tlv(0x04, ext_value)
    )

    # Split the TBS content at the [3] extensions element (if any).
    off = tbs_off
    t2 = _tlv(der, off, tbs_end)
    if t2 is not None and t2[0] == 0xA0:
        off = t2[1] + t2[2]
    for _ in range(6):
        t2 = _tlv(der, off, tbs_end)
        if t2 is None:
            raise ValueError("truncated TBSCertificate")
        off = t2[1] + t2[2]
    head = der[tbs_off:off]
    exts_content = b""
    while off < tbs_end:
        t2 = _tlv(der, off, tbs_end)
        if t2 is None:
            raise ValueError("bad trailing TBS element")
        if t2[0] == 0xA3:
            seq = _tlv(der, t2[1], t2[1] + t2[2])
            if seq is None or seq[0] != 0x30:
                raise ValueError("bad extensions element")
            exts_content = der[seq[1] : seq[1] + seq[2]]
            off = t2[1] + t2[2]
            break
        head += der[off : t2[1] + t2[2]]
        off = t2[1] + t2[2]
    head += der[off:tbs_end]  # anything after [3] (none in practice)

    new_exts = _wrap_tlv(0xA3, _wrap_tlv(0x30, exts_content + sct_ext))
    new_tbs = _wrap_tlv(0x30, head + new_exts)
    new_cert = _wrap_tlv(0x30, new_tbs + rest)

    win = find_sct_extension(new_cert)
    if win is None:
        raise RuntimeError("embedded SCT extension not found back")
    tlv_off, tlv_end, v_off, _v_end = win
    digest = sct_digest(new_cert, tlv_off, tlv_end, timestamp_ms,
                        extensions, issuer_key_hash)
    if digest is None:
        raise RuntimeError("TBS reconstruction failed on own output")
    sig = bytearray(signer.sign(digest))
    if len(sig) != signer.sig_len:
        raise RuntimeError("signer broke its fixed-length contract")
    if corrupt_signature:
        sig[-1] ^= 0x01
    sig_off = v_off + 4 + 1 + 32 + 8 + 2 + len(extensions) + 1 + 1 + 2
    out = bytearray(new_cert)
    out[sig_off : sig_off + len(sig)] = sig
    return bytes(out)


def host_verify_sct(digest: bytes, sct: ParsedSct, key: dict) -> bool:
    """The host-lane verdict for one extracted SCT against a registry
    key entry (see :class:`~ct_mapreduce_tpu.verify.lane.
    LogKeyRegistry`). Malformed-for-its-algorithm signatures fail
    closed; the caller has already resolved key presence."""
    if sct.version != 0 or sct.hash_alg != HASH_SHA256:
        return False
    alg = key.get("alg")
    if alg == "rsa":
        if sct.sig_alg != SIG_RSA:
            return False
        return host.verify_rsa_pkcs1_sha256(
            digest, sct.signature, int(key["n"], 16), int(key["e"], 16)
        )
    curve = host.CURVES.get(alg)
    if curve is None or sct.sig_alg != SIG_ECDSA:
        return False
    rs = parse_ecdsa_sig(sct.signature, curve.byte_len)
    if rs is None:
        return False
    return host.verify_ecdsa(
        curve, digest, rs[0], rs[1], int(key["x"], 16), int(key["y"], 16)
    )
