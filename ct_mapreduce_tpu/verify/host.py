"""Pure-python signature verification — the exactness reference.

The device kernel (:mod:`ct_mapreduce_tpu.ops.ecdsa`) must produce
verdicts bit-identical to :func:`verify_ecdsa` over P-256 on every
input; the known-answer corpus and mutation fuzz in
tests/test_ecdsa.py pin that. This module is also the *fallback lane*:
signatures the extractor routes around the device kernel (odd curves,
RSA) verify here, so every SCT gets the same-math verdict regardless
of which lane decided it — the walker-fallback contract applied to
verification.

Dependency-free (python ints + hashlib): runs on hosts without the
``cryptography`` package, same degradation contract as the minicert
fixtures.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass


@dataclass(frozen=True)
class Curve:
    """Short-Weierstrass curve y² = x³ + ax + b over GF(p)."""

    name: str
    p: int
    n: int  # group order (prime)
    a: int
    b: int
    gx: int
    gy: int

    @property
    def byte_len(self) -> int:
        return (self.p.bit_length() + 7) // 8


P256 = Curve(
    name="p256",
    p=0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF,
    n=0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551,
    a=-3,
    b=0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B,
    gx=0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296,
    gy=0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5,
)

P384 = Curve(
    name="p384",
    p=int("fffffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff"
          "effffffff0000000000000000ffffffff", 16),
    n=int("ffffffffffffffffffffffffffffffffffffffffffffffffc7634d81f4372dd"
          "f581a0db248b0a77aecec196accc52973", 16),
    a=-3,
    b=int("b3312fa7e23ee7e4988e056be3f82d19181d9c6efe8141120314088f5013875a"
          "c656398d8a2ed19d2a85c8edd3ec2aef", 16),
    gx=int("aa87ca22be8b05378eb1c71ef320ad746e1d3b628ba79b9859f741e082542a3"
           "85502f25dbf55296c3a545e3872760ab7", 16),
    gy=int("3617de4a96262c6f5d9e98bf9292dc29f8f41dbd289a147ce9da3113b5f0b8c"
           "00a60b1ce1d7e819d7a431d7c90ea0e5f", 16),
)

CURVES = {c.name: c for c in (P256, P384)}


def _point_add(c: Curve, P, Q):
    """Affine group law; None is the point at infinity."""
    if P is None:
        return Q
    if Q is None:
        return P
    x1, y1 = P
    x2, y2 = Q
    if x1 == x2:
        if (y1 + y2) % c.p == 0:
            return None
        lam = (3 * x1 * x1 + c.a) * pow(2 * y1, -1, c.p) % c.p
    else:
        lam = (y2 - y1) * pow(x2 - x1, -1, c.p) % c.p
    x3 = (lam * lam - x1 - x2) % c.p
    return x3, (lam * (x1 - x3) - y1) % c.p


def _point_mul(c: Curve, k: int, P):
    R = None
    while k:
        if k & 1:
            R = _point_add(c, R, P)
        P = _point_add(c, P, P)
        k >>= 1
    return R


def digest_to_z(c: Curve, digest: bytes) -> int:
    """Leftmost min(hashbits, nbits) bits of the digest (SEC1 §4.1.4)."""
    z = int.from_bytes(digest, "big")
    excess = len(digest) * 8 - c.n.bit_length()
    if excess > 0:
        z >>= excess
    return z


def verify_ecdsa(c: Curve, digest: bytes, r: int, s: int,
                 x: int, y: int) -> bool:
    """The reference ECDSA verdict. Every check the device kernel
    makes, in the same semantics: range-check r/s, range- and
    curve-check the public key, compare r to x_R mod n."""
    if not (1 <= r < c.n and 1 <= s < c.n):
        return False
    if not (0 <= x < c.p and 0 <= y < c.p) or (x == 0 and y == 0):
        return False
    if (y * y - (x * x * x + c.a * x + c.b)) % c.p != 0:
        return False
    w = pow(s, -1, c.n)
    z = digest_to_z(c, digest)
    u1 = z * w % c.n
    u2 = r * w % c.n
    R = _point_add(
        c,
        _point_mul(c, u1, (c.gx, c.gy)),
        _point_mul(c, u2, (x, y)),
    )
    if R is None:
        return False
    return R[0] % c.n == r


def sign_ecdsa(c: Curve, digest: bytes, d: int, k: int) -> tuple[int, int]:
    """Deterministic-nonce signing for FIXTURES ONLY (the nonce is
    caller-supplied; nothing here is a secure signer). Returns (r, s);
    raises if the nonce degenerates (re-pick upstream)."""
    R = _point_mul(c, k, (c.gx, c.gy))
    if R is None:
        raise ValueError("degenerate nonce")
    r = R[0] % c.n
    s = pow(k, -1, c.n) * (digest_to_z(c, digest) + r * d) % c.n
    if r == 0 or s == 0:
        raise ValueError("degenerate signature")
    return r, s


# -- RSA PKCS#1 v1.5 (the fallback for RSA-signed SCTs) -----------------

_SHA256_DIGESTINFO = bytes.fromhex(
    "3031300d060960864801650304020105000420"
)


def verify_rsa_pkcs1_sha256(digest: bytes, sig: bytes,
                            n: int, e: int) -> bool:
    """RSA PKCS#1 v1.5 over a precomputed SHA-256 digest."""
    k = (n.bit_length() + 7) // 8
    if len(sig) != k:
        return False
    m = pow(int.from_bytes(sig, "big"), e, n)
    em = m.to_bytes(k, "big")
    ps = k - len(_SHA256_DIGESTINFO) - len(digest) - 3
    expect = (b"\x00\x01" + b"\xff" * ps + b"\x00"
              + _SHA256_DIGESTINFO + digest)
    return em == expect


def sign_rsa_pkcs1_sha256(digest: bytes, n: int, d: int) -> bytes:
    """Fixture-only PKCS#1 v1.5 signing."""
    k = (n.bit_length() + 7) // 8
    ps = k - len(_SHA256_DIGESTINFO) - len(digest) - 3
    em = (b"\x00\x01" + b"\xff" * ps + b"\x00"
          + _SHA256_DIGESTINFO + digest)
    return pow(int.from_bytes(em, "big"), d, n).to_bytes(k, "big")


def derive_scalar(seed: str, c: Curve = P256) -> int:
    """Deterministic private scalar for fixture keys: d ∈ [1, n-1]."""
    h = int.from_bytes(
        hashlib.sha512(b"ctmr-log-key:" + seed.encode()).digest(), "big"
    )
    return h % (c.n - 1) + 1


def derive_nonce(seed: str, digest: bytes, c: Curve = P256) -> int:
    """Deterministic fixture nonce (NOT RFC 6979; test-only)."""
    h = int.from_bytes(
        hashlib.sha512(b"ctmr-k:" + seed.encode() + digest).digest(), "big"
    )
    return h % (c.n - 1) + 1
