"""The ingest-side signature-verification lane (``verifySignatures``).

``SignatureVerifier`` sits beside the dedup dispatch in
``AggregatorSink``: each prepared chunk's extracted SCT tuples
(:func:`ct_mapreduce_tpu.native.leafpack.extract_scts`) are classified
per lane —

- **device** — P-256-shaped SCT (extractor status ``SCT_OK``) whose
  log key is a registered P-256 key: staged into a fixed-width batch
  for the jitted :func:`ct_mapreduce_tpu.ops.ecdsa.verify_p256_jit`
  kernel, dispatched asynchronously (the pending deque mirrors the
  sink's dedup pipelining), folded under the aggregator's fold lock.
- **host fallback** — SCT present but not device-decidable (odd
  curves, RSA signatures, malformed DER innards — extractor status
  ``SCT_FALLBACK``), or device-shaped but keyed to a non-P-256 log:
  replayed through the pure-python reference verifier from the lane's
  row bytes. Verdicts are bit-identical to the host verifier by
  construction on BOTH lanes — the device kernel is parity-pinned
  against the same reference.
- **no_key / no_sct** — counted, not judged: an unregistered log id
  cannot be verified anywhere, and most certs simply carry no SCT.

Results land on the aggregator as per-issuer verified/failed vectors
(surfaced via drain()/storage-statistics, the query plane's
``/issuer/<id>``, and checkpoints) plus ``verify.*`` telemetry
counters and ``device.verify`` spans.
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Optional

import numpy as np

from ct_mapreduce_tpu.telemetry import trace
from ct_mapreduce_tpu.telemetry.metrics import add_sample, incr_counter
from ct_mapreduce_tpu.verify import sct as sctlib

DEFAULT_BATCH = 1024


def resolve_verify(flag: Optional[bool] = None,
                   keys_path: Optional[str] = None,
                   batch: int = 0) -> tuple[bool, str, int]:
    """Resolve the verify-lane knobs: explicit value (config directive
    / kwarg) > ``CTMR_VERIFY`` / ``CTMR_VERIFY_KEYS`` /
    ``CTMR_VERIFY_BATCH`` env > defaults (off; no key file; 1024-lane
    device batches). Unparseable env values are ignored, matching the
    config layer's tolerance."""
    if flag is None:
        flag = os.environ.get("CTMR_VERIFY", "0") == "1"
    if not keys_path:
        keys_path = os.environ.get("CTMR_VERIFY_KEYS", "")
    b = int(batch or 0)
    if b <= 0:
        try:
            b = int(os.environ.get("CTMR_VERIFY_BATCH", "0") or 0)
        except ValueError:
            b = 0
    return bool(flag), keys_path, (b if b > 0 else DEFAULT_BATCH)


class LogKeyRegistry:
    """log_id (32 bytes) → key entry dict, the trust anchors of the
    verify lane. Entries are the JSON shape the fixture signers emit
    (:meth:`~ct_mapreduce_tpu.verify.sct.EcSctSigner.key_entry`):
    ``{"log_id": hex, "alg": "p256"|"p384"|"rsa", ...}``."""

    def __init__(self) -> None:
        self._keys: dict[bytes, dict] = {}
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._keys)

    def register(self, entry: dict) -> None:
        with self._lock:
            self._keys[bytes.fromhex(entry["log_id"])] = dict(entry)

    def register_signer(self, signer) -> None:
        self.register(signer.key_entry())

    def get(self, log_id: bytes) -> Optional[dict]:
        return self._keys.get(log_id)

    def is_p256(self, log_id: bytes) -> bool:
        e = self._keys.get(log_id)
        return e is not None and e.get("alg") == "p256"

    def to_json(self) -> str:
        with self._lock:
            entries = [
                {k: v for k, v in e.items() if not k.startswith("_")}
                for e in self._keys.values()
            ]  # "_"-prefixed keys are runtime caches (_key_coord)
            return json.dumps(sorted(entries, key=lambda e: e["log_id"]))

    @classmethod
    def from_json_file(cls, path: str) -> "LogKeyRegistry":
        reg = cls()
        with open(path) as fh:
            for entry in json.load(fh):
                reg.register(entry)
        return reg


class _PendingVerify:
    """One dispatched device verify batch awaiting readback."""

    def __init__(self, out, n: int, issuer_idx: np.ndarray) -> None:
        self.out = out  # device bool[width]
        self.n = n
        self.issuer_idx = issuer_idx  # int32[n]


class SignatureVerifier:
    """Batches device-eligible SCT lanes across chunks and folds
    verdicts into the aggregator. All entry points are called under
    the sink's dispatch lock (one device stream), so internal state
    needs no extra locking; aggregator folds take the fold lock."""

    def __init__(self, agg, keys: Optional[LogKeyRegistry] = None,
                 batch_width: int = DEFAULT_BATCH, depth: int = 2) -> None:
        self.agg = agg
        self.keys = keys if keys is not None else LogKeyRegistry()
        self.batch_width = max(16, int(batch_width))
        self.depth = max(0, int(depth))
        self._buf: list[tuple] = []  # (digest, r, s, qx, qy, issuer_idx)
        self._inflight: deque[_PendingVerify] = deque()
        # Scalar outcomes (also exported as verify.* counters; kept
        # here so tests and the bench leg can read exact totals).
        self.stats = {
            "device_lanes": 0, "host_lanes": 0, "no_sct": 0,
            "no_key": 0, "verified": 0, "failed": 0, "batches": 0,
        }

    # -- classification + staging ---------------------------------------
    def submit_chunk(self, scts: sctlib.SctBatch, issuer_idx: np.ndarray,
                     eligible: np.ndarray, rows: np.ndarray,
                     lengths: np.ndarray) -> None:
        """Route one prepared chunk's SCT lanes. ``eligible`` marks
        lanes that decoded OK with a mapped issuer (the verify universe
        — filtered/duplicate lanes still carry auditable SCTs)."""
        eligible = np.asarray(eligible, bool)
        ok = scts.ok
        no_sct = int((eligible & (ok == sctlib.SCT_NONE)).sum())
        if no_sct:
            self.stats["no_sct"] += no_sct
            incr_counter("verify", "no_sct", value=float(no_sct))
        lanes = np.nonzero(eligible & (ok != sctlib.SCT_NONE))[0]
        host_lanes: list[int] = []
        for i in lanes:
            i = int(i)
            log_id = scts.log_id[i].tobytes()
            key = self.keys.get(log_id)
            if key is None:
                self.stats["no_key"] += 1
                incr_counter("verify", "no_key")
                continue
            if ok[i] == sctlib.SCT_OK and key.get("alg") == "p256":
                self._buf.append((
                    scts.digest[i], scts.r[i], scts.s[i],
                    _key_coord(key, "x"), _key_coord(key, "y"),
                    int(issuer_idx[i]),
                ))
            else:
                host_lanes.append(i)
        if host_lanes:
            self._host_verify(host_lanes, scts, issuer_idx, rows, lengths)
        while len(self._buf) >= self.batch_width:
            self._dispatch(self.batch_width)
        self._drain_inflight(self.depth)

    def _host_verify(self, lanes, scts, issuer_idx, rows, lengths) -> None:
        """The fallback lane: re-extract each lane's SCT from its row
        bytes (the compact batch doesn't carry fallback signatures) and
        judge it with the pure-python reference verifier."""
        verdicts = np.zeros((len(lanes),), bool)
        idx = np.zeros((len(lanes),), np.int64)
        for j, i in enumerate(lanes):
            der = rows[i, : int(lengths[i])].tobytes()
            _status, sc, digest, _r, _s = sctlib.extract_sct_lane(der)
            key = self.keys.get(scts.log_id[i].tobytes())
            verdicts[j] = (sc is not None and key is not None
                           and sctlib.host_verify_sct(digest, sc, key))
            idx[j] = int(issuer_idx[i])
        self.stats["host_lanes"] += len(lanes)
        incr_counter("verify", "host_lanes", value=float(len(lanes)))
        self._fold_verdicts(verdicts, idx)

    # -- device lane -----------------------------------------------------
    def _dispatch(self, take: int) -> None:
        from ct_mapreduce_tpu.ops import ecdsa

        batch, self._buf = self._buf[:take], self._buf[take:]
        n = len(batch)
        w = self.batch_width  # ONE compiled width per verifier
        arr = lambda k: np.stack([b[k] for b in batch])  # noqa: E731

        def pad(a):
            return np.pad(np.ascontiguousarray(a, np.uint8),
                          ((0, w - n), (0, 0)))

        valid = np.pad(np.ones((n,), bool), (0, w - n))
        with trace.span("device.verify", cat="device", lanes=n):
            out = ecdsa.verify_p256_jit(
                pad(arr(0)), pad(arr(1)), pad(arr(2)),
                pad(arr(3)), pad(arr(4)), valid,
            )
        self.stats["batches"] += 1
        self.stats["device_lanes"] += n
        incr_counter("verify", "batches")
        incr_counter("verify", "device_lanes", value=float(n))
        add_sample("verify", "batch_lanes", value=float(n))
        self._inflight.append(_PendingVerify(
            out, n, np.array([b[5] for b in batch], np.int64)))

    def _drain_inflight(self, keep: int) -> None:
        while len(self._inflight) > keep:
            p = self._inflight.popleft()
            verdicts = np.asarray(p.out)[: p.n]  # the blocking read
            self._fold_verdicts(verdicts, p.issuer_idx)

    def _fold_verdicts(self, verdicts: np.ndarray,
                       issuer_idx: np.ndarray) -> None:
        if len(verdicts) == 0:
            return
        v = int(verdicts.sum())
        f = len(verdicts) - v
        self.stats["verified"] += v
        self.stats["failed"] += f
        if v:
            incr_counter("verify", "verified", value=float(v))
        if f:
            incr_counter("verify", "failed", value=float(f))
        agg = self.agg
        with agg._fold_lock:
            agg.grow_verify_totals(int(issuer_idx.max(initial=0)))
            np.add.at(agg.verify_verified, issuer_idx, verdicts)
            np.add.at(agg.verify_failed, issuer_idx, ~verdicts)

    def drain(self) -> None:
        """Flush the staging buffer (padding the tail to the compiled
        width) and fold every outstanding batch."""
        while self._buf:
            self._dispatch(min(len(self._buf), self.batch_width))
        self._drain_inflight(0)


def _key_coord(key: dict, name: str) -> np.ndarray:
    c = key.get(f"_{name}_bytes")
    if c is None:
        c = np.frombuffer(
            int(key[name], 16).to_bytes(32, "big"), np.uint8)
        key[f"_{name}_bytes"] = c  # parse hex once per key
    return c
