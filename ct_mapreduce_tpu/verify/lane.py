"""The ingest-side signature-verification lane (``verifySignatures``).

``SignatureVerifier`` sits beside the dedup dispatch in
``AggregatorSink``: each prepared chunk's extracted SCT tuples
(:func:`ct_mapreduce_tpu.native.leafpack.extract_scts`) are classified
per lane —

- **device P-256** — P-256-shaped SCT (extractor status ``SCT_OK``)
  whose log key is a registered P-256 key: staged into a fixed-width
  batch for the jitted ECDSA kernels (:mod:`ct_mapreduce_tpu.ops.
  ecdsa`), dispatched asynchronously (the pending deque mirrors the
  sink's dedup pipelining), folded under the aggregator's fold lock.
- **device P-384 (round 17)** — a lane keyed to a registered P-384
  log replays its SCT from the row bytes (the compact batch carries
  only 32-byte scalars) and, when it is a well-formed SHA-256/ECDSA
  signature, batches onto the P-384 kernel the same way. Malformed-
  for-the-algorithm lanes still fall back to the host verifier, which
  fails them closed exactly as the device range checks would.
- **host fallback** — SCT present but not device-decidable (RSA
  signatures, unregistered-curve keys, malformed DER innards):
  replayed through the pure-python reference verifier from the lane's
  row bytes. Verdicts are bit-identical to the host verifier by
  construction on EVERY lane — the device kernels are parity-pinned
  against the same reference.
- **no_key / no_sct** — counted, not judged: an unregistered log id
  cannot be verified anywhere, and most certs simply carry no SCT.

Round 17 (`verifyPrecompWindow` > 0, the default): the device lanes
run the windowed-precompute kernels — u1·G reads the process-wide
fixed-base G table, u2·Q reads a per-log-key window table cached in a
device-resident LRU (``verifyQTableSize`` slots, keyed on the
registry entry + its registration epoch so re-registered keys
invalidate only themselves). A CT workload verifies millions of
signatures under <100 distinct log keys, so the steady state is 100%
``verify.qtable_hits`` and the dual-scalar ladder degenerates into
table-lookup additions. ``verifyPrecompWindow = 0`` restores the
round-13 Jacobian ladder (the parity fallback).

Results land on the aggregator as per-issuer verified/failed vectors
(surfaced via drain()/storage-statistics, the query plane's
``/issuer/<id>``, and checkpoints) plus ``verify.*`` telemetry
counters and ``device.verify`` spans; qtable occupancy rides the
/healthz ``verify`` section.
"""

from __future__ import annotations

import json
import os
import threading
from collections import OrderedDict, deque
from typing import Optional

import numpy as np

from ct_mapreduce_tpu.config import profile as platprofile
from ct_mapreduce_tpu.telemetry import trace
from ct_mapreduce_tpu.telemetry.metrics import (
    add_sample,
    incr_counter,
    set_gauge,
)
from ct_mapreduce_tpu.verify import sct as sctlib

DEFAULT_BATCH = 1024
DEFAULT_WINDOW = 8  # keep in sync with ops.ecdsa.DEFAULT_WINDOW
VALID_WINDOWS = (0, 2, 4, 8)
DEFAULT_QTABLE = 32  # per-curve device-resident Q-table slots

_VERIFY_KNOBS = (
    platprofile.Knob("verifySignatures", "CTMR_VERIFY", False,
                     parse=lambda s: s.strip() == "1",
                     env_is_set=platprofile.any_set, post=bool),
    platprofile.Knob("verifyLogKeys", "CTMR_VERIFY_KEYS", "",
                     parse=str, is_set=platprofile.nonempty_str),
    platprofile.Knob("verifyBatch", "CTMR_VERIFY_BATCH", DEFAULT_BATCH,
                     parse=int, is_set=platprofile.pos_int,
                     post=lambda v: int(v)),
    # -1 = unset; 0 is a REAL value (the legacy Jacobian ladder), so
    # an explicit 0 must beat a stray env var.
    platprofile.Knob("verifyPrecompWindow", "CTMR_VERIFY_PRECOMP_WINDOW",
                     -1, parse=int, is_set=platprofile.nonneg_int),
    platprofile.Knob("verifyQTableSize", "CTMR_VERIFY_QTABLE_SIZE",
                     DEFAULT_QTABLE, parse=int,
                     is_set=platprofile.pos_int,
                     post=lambda v: int(v)),
)


def resolve_verify(flag: Optional[bool] = None,
                   keys_path: Optional[str] = None,
                   batch: int = 0,
                   window: Optional[int] = None,
                   qtable_size: int = 0,
                   ) -> tuple[bool, str, int, int, int]:
    """Resolve the verify-lane knobs through the shared
    platformProfile ladder (config/profile.py): explicit value (config
    directive / kwarg) > ``CTMR_VERIFY`` / ``CTMR_VERIFY_KEYS`` /
    ``CTMR_VERIFY_BATCH`` / ``CTMR_VERIFY_PRECOMP_WINDOW`` /
    ``CTMR_VERIFY_QTABLE_SIZE`` env > profile ``knobs.verify`` >
    defaults (off; no key file; 1024-lane device batches; 8-bit
    precompute windows; 32 Q-table slots). ``window = 0`` selects the
    legacy Jacobian ladder; unparseable env values are ignored,
    matching the config layer's tolerance."""
    r = platprofile.resolve_section("verify", _VERIFY_KNOBS, {
        "verifySignatures": flag,
        "verifyLogKeys": keys_path or "",
        "verifyBatch": int(batch or 0),
        "verifyPrecompWindow": (-1 if window is None else int(window)),
        "verifyQTableSize": int(qtable_size or 0),
    })
    w = int(r["verifyPrecompWindow"])
    if w < 0 or w not in VALID_WINDOWS:
        w = DEFAULT_WINDOW if w != 0 else 0
    return (r["verifySignatures"], r["verifyLogKeys"],
            r["verifyBatch"], w, r["verifyQTableSize"])


class LogKeyRegistry:
    """log_id (32 bytes) → key entry dict, the trust anchors of the
    verify lane. Entries are the JSON shape the fixture signers emit
    (:meth:`~ct_mapreduce_tpu.verify.sct.EcSctSigner.key_entry`):
    ``{"log_id": hex, "alg": "p256"|"p384"|"rsa", ...}``. Every
    registration stamps the entry with a monotonically increasing
    registry epoch (``_epoch``) — the Q-table cache keys on it, so
    re-registering a log id invalidates exactly that key's cached
    precompute and nothing else."""

    def __init__(self) -> None:
        self._keys: dict[bytes, dict] = {}
        self._lock = threading.Lock()
        self._epoch = 0

    def __len__(self) -> int:
        return len(self._keys)

    @property
    def epoch(self) -> int:
        return self._epoch

    def register(self, entry: dict) -> None:
        with self._lock:
            e = dict(entry)
            self._epoch += 1
            e["_epoch"] = self._epoch
            self._keys[bytes.fromhex(entry["log_id"])] = e

    def register_signer(self, signer) -> None:
        self.register(signer.key_entry())

    def get(self, log_id: bytes) -> Optional[dict]:
        return self._keys.get(log_id)

    def is_p256(self, log_id: bytes) -> bool:
        e = self._keys.get(log_id)
        return e is not None and e.get("alg") == "p256"

    def to_json(self) -> str:
        with self._lock:
            entries = [
                {k: v for k, v in e.items() if not k.startswith("_")}
                for e in self._keys.values()
            ]  # "_"-prefixed keys are runtime caches (_key_coord, epoch)
            return json.dumps(sorted(entries, key=lambda e: e["log_id"]))

    def entries(self) -> list[dict]:
        """Snapshot of the registered entries (runtime fields
        included), sorted by log id — what the Q-table prebuild
        walks."""
        with self._lock:
            return [self._keys[k] for k in sorted(self._keys)]

    @classmethod
    def from_json_file(cls, path: str) -> "LogKeyRegistry":
        reg = cls()
        with open(path) as fh:
            for entry in json.load(fh):
                reg.register(entry)
        return reg


class _PendingVerify:
    """One dispatched device verify batch awaiting readback."""

    def __init__(self, out, n: int, issuer_idx: np.ndarray) -> None:
        self.out = out  # device bool[width]
        self.n = n
        self.issuer_idx = issuer_idx  # int32[n]


class _CurveLane:
    """Per-curve device staging state: the staging buffer, the
    fixed-base G table, and the device-resident Q-table slots."""

    def __init__(self, ops, window: int, slots: int) -> None:
        self.ops = ops
        self.window = window
        self.capacity = max(1, int(slots))
        self.buf: list[tuple] = []  # (digest, r, s, qx, qy, iidx, tabkey)
        self.slot_of: "OrderedDict[tuple, int]" = OrderedDict()  # LRU
        self.gtab = None  # device [nwin, 2^w, 2, nl]
        self.qtab = None  # device [capacity, nwin, 2^w, 2, nl]

    def occupancy(self) -> int:
        return len(self.slot_of)


class SignatureVerifier:
    """Batches device-eligible SCT lanes across chunks and folds
    verdicts into the aggregator. All entry points are called under
    the sink's dispatch lock (one device stream), so internal state
    needs no extra locking; aggregator folds take the fold lock and
    precompute-table builds take the ops-layer table lock (rank 22,
    under dispatch in the declared hierarchy)."""

    def __init__(self, agg, keys: Optional[LogKeyRegistry] = None,
                 batch_width: int = DEFAULT_BATCH, depth: int = 2,
                 window: Optional[int] = None,
                 qtable_size: int = 0) -> None:
        self.agg = agg
        self.keys = keys if keys is not None else LogKeyRegistry()
        self.batch_width = max(16, int(batch_width))
        self.depth = max(0, int(depth))
        _, _, _, self.window, self.qtable_size = resolve_verify(
            True, "x", 1, window, qtable_size)
        self._lanes: dict[str, _CurveLane] = {}  # curve name → staging
        self._inflight: deque[_PendingVerify] = deque()
        set_gauge("verify", "precomp_window", value=float(self.window))
        # Scalar outcomes (also exported as verify.* counters; kept
        # here so tests and the bench leg can read exact totals).
        self.stats = {
            "device_lanes": 0, "host_lanes": 0, "no_sct": 0,
            "no_key": 0, "verified": 0, "failed": 0, "batches": 0,
            "p384_lanes": 0, "qtable_hits": 0, "qtable_misses": 0,
        }
        # Q-table prebuild (round 20, ROADMAP 3): warm the host-side
        # window table for EVERY registered key at startup on a
        # background thread, so the first dispatch under each key hits
        # the process-wide cache instead of paying the table build
        # inline (the first-dispatch latency spike). point_table_cached
        # is lock-guarded and keyed on coordinates — a dispatch racing
        # the prebuild at worst builds the same table first and the
        # prebuild's call becomes a cache hit.
        self._prebuild_thread = None
        if self.window > 0 and len(self.keys):
            self._prebuild_thread = threading.Thread(
                target=self._prebuild_qtables,
                name="verify-qtable-prebuild", daemon=True)
            self._prebuild_thread.start()

    def _prebuild_qtables(self) -> None:
        from ct_mapreduce_tpu.ops import ecdsa

        for e in self.keys.entries():
            alg = e.get("alg")
            if alg not in ecdsa.CURVE_OPS:
                continue
            try:
                _, build_s = ecdsa.point_table_cached(
                    ecdsa.CURVE_OPS[alg], self.window,
                    int(e["x"], 16), int(e["y"], 16))
            except (KeyError, ValueError):
                continue  # malformed entry: the dispatch path reports
            if build_s > 0.0:
                add_sample("verify", "qtable_build_s", value=build_s)
                incr_counter("verify", "qtable_prebuilt")

    # -- classification + staging ---------------------------------------
    def submit_chunk(self, scts: sctlib.SctBatch, issuer_idx: np.ndarray,
                     eligible: np.ndarray, rows: np.ndarray,
                     lengths: np.ndarray) -> None:
        """Route one prepared chunk's SCT lanes. ``eligible`` marks
        lanes that decoded OK with a mapped issuer (the verify universe
        — filtered/duplicate lanes still carry auditable SCTs)."""
        eligible = np.asarray(eligible, bool)
        ok = scts.ok
        no_sct = int((eligible & (ok == sctlib.SCT_NONE)).sum())
        if no_sct:
            self.stats["no_sct"] += no_sct
            incr_counter("verify", "no_sct", value=float(no_sct))
        lanes = np.nonzero(eligible & (ok != sctlib.SCT_NONE))[0]
        host_lanes: list[int] = []
        for i in lanes:
            i = int(i)
            log_id = scts.log_id[i].tobytes()
            key = self.keys.get(log_id)
            if key is None:
                self.stats["no_key"] += 1
                incr_counter("verify", "no_key")
                continue
            alg = key.get("alg")
            if ok[i] == sctlib.SCT_OK and alg == "p256":
                self._lane("p256").buf.append((
                    scts.digest[i], scts.r[i], scts.s[i],
                    _key_coord(key, "x"), _key_coord(key, "y"),
                    int(issuer_idx[i]), _table_key(log_id, key),
                ))
            elif alg == "p384" and not self._stage_p384(
                    i, log_id, key, scts, issuer_idx, rows, lengths):
                host_lanes.append(i)
            elif alg not in ("p256", "p384"):
                host_lanes.append(i)
            elif alg == "p256":  # SCT_FALLBACK under a p256 key
                host_lanes.append(i)
        if host_lanes:
            self._host_verify(host_lanes, scts, issuer_idx, rows, lengths)
        for lane in self._lanes.values():
            while len(lane.buf) >= self.batch_width:
                self._dispatch(lane, self.batch_width)
        self._drain_inflight(self.depth)

    def _stage_p384(self, i: int, log_id: bytes, key: dict, scts,
                    issuer_idx, rows, lengths) -> bool:
        """Re-extract lane ``i``'s SCT from its row bytes and stage it
        for the P-384 kernel when it is device-decidable: exactly the
        preconditions :func:`~ct_mapreduce_tpu.verify.sct.
        host_verify_sct` applies before its P-384 curve math, so a
        lane routed here gets the same-math verdict it would have
        gotten from the host fallback. Returns False (→ host lane,
        which fails it closed) otherwise."""
        der = rows[i, : int(lengths[i])].tobytes()
        _status, sc, _digest, _r, _s = sctlib.extract_sct_lane(der)
        if (sc is None or sc.version != 0
                or sc.hash_alg != sctlib.HASH_SHA256
                or sc.sig_alg != sctlib.SIG_ECDSA):
            return False
        rs = sctlib.parse_ecdsa_sig(sc.signature, 48)
        if rs is None:
            return False
        dg = np.zeros((48,), np.uint8)
        # The batch digest, not the re-extracted one: only the batch
        # carries the lane's issuer_key_hash (the re-extraction here
        # is for the signature bytes the compact batch drops).
        dg[16:] = scts.digest[i]
        self._lane("p384").buf.append((
            dg,
            np.frombuffer(rs[0].to_bytes(48, "big"), np.uint8),
            np.frombuffer(rs[1].to_bytes(48, "big"), np.uint8),
            _key_coord(key, "x", 48), _key_coord(key, "y", 48),
            int(issuer_idx[i]), _table_key(log_id, key),
        ))
        self.stats["p384_lanes"] += 1
        incr_counter("verify", "p384_lanes")
        return True

    def _host_verify(self, lanes, scts, issuer_idx, rows, lengths) -> None:
        """The fallback lane: re-extract each lane's SCT from its row
        bytes (the compact batch doesn't carry fallback signatures) and
        judge it with the pure-python reference verifier."""
        verdicts = np.zeros((len(lanes),), bool)
        idx = np.zeros((len(lanes),), np.int64)
        for j, i in enumerate(lanes):
            der = rows[i, : int(lengths[i])].tobytes()
            _status, sc, _digest, _r, _s = sctlib.extract_sct_lane(der)
            key = self.keys.get(scts.log_id[i].tobytes())
            # Judge against the BATCH digest — it carries the lane's
            # issuer_key_hash; the re-extraction only recovers the
            # signature bytes the compact batch drops.
            verdicts[j] = (sc is not None and key is not None
                           and sctlib.host_verify_sct(
                               scts.digest[i].tobytes(), sc, key))
            idx[j] = int(issuer_idx[i])
        self.stats["host_lanes"] += len(lanes)
        incr_counter("verify", "host_lanes", value=float(len(lanes)))
        self._fold_verdicts(verdicts, idx)

    # -- device lane -----------------------------------------------------
    def _lane(self, curve: str) -> _CurveLane:
        lane = self._lanes.get(curve)
        if lane is None:
            from ct_mapreduce_tpu.ops import ecdsa

            lane = _CurveLane(ecdsa.CURVE_OPS[curve], self.window,
                              self.qtable_size)
            self._lanes[curve] = lane
        return lane

    def _ensure_tables(self, lane: _CurveLane) -> None:
        """Materialize the curve's G table + empty Q-table slots on
        device (first dispatch only). Build time rides the
        verify.precomp_build_s sample when the process-wide cache
        missed."""
        if lane.gtab is not None or lane.window == 0:
            return
        from ct_mapreduce_tpu.ops import ecdsa

        lane.gtab, build_s = ecdsa.fixed_base_table(lane.ops, lane.window)
        if build_s > 0.0:
            add_sample("verify", "precomp_build_s", value=build_s)
        nl = lane.ops.mod_p.nlimb
        # Device slots are pow2-padded with the wrapper's floor so the
        # kernel compiles ONE qtab shape per (curve, window, width)
        # regardless of the logical LRU capacity (compile shapes stay
        # log-bounded; eviction is governed by `capacity` alone).
        slots = max(ecdsa.MIN_QTABLE_SLOTS,
                    1 << max(0, (lane.capacity - 1).bit_length()))
        lane.qtab = ecdsa.zero_qtable(
            slots, lane.ops.nbits // lane.window,
            1 << lane.window, nl)

    def _resolve_slots(self, lane: _CurveLane,
                       batch: list[tuple]) -> tuple[np.ndarray, int]:
        """Map staged lanes' table keys to device Q-table slots,
        building + shipping missing tables (LRU eviction reuses the
        stalest slot). Slots referenced by THIS batch are pinned —
        eviction may only reclaim a slot no earlier lane of the batch
        reads, so an over-subscribed dispatch can never serve a lane
        from an overwritten table. Returns ``(slots, consumed)``;
        consumed < len(batch) when the batch holds more distinct keys
        than the cache holds slots (the caller splits the dispatch).
        Steady state — <100 log keys, table slots ≥ live keys — is
        100% hits and zero H2D traffic."""
        from ct_mapreduce_tpu.ops import ecdsa

        slots = np.zeros((len(batch),), np.int32)
        pinned: set[int] = set()
        for j, entry in enumerate(batch):
            tabkey = entry[6]
            slot = lane.slot_of.get(tabkey)
            if slot is not None:
                lane.slot_of.move_to_end(tabkey)
                self.stats["qtable_hits"] += 1
                incr_counter("verify", "qtable_hits")
            else:
                if len(lane.slot_of) >= lane.capacity:
                    victim = next(
                        (k for k, sl in lane.slot_of.items()
                         if sl not in pinned), None)
                    if victim is None:  # every slot pinned: split here
                        return slots[:j], j
                    slot = lane.slot_of.pop(victim)
                else:
                    slot = len(lane.slot_of)
                lane.slot_of[tabkey] = slot
                np_tab, build_s = ecdsa.point_table_cached(
                    lane.ops, lane.window, tabkey[2], tabkey[3])
                if build_s > 0.0:
                    add_sample("verify", "qtable_build_s", value=build_s)
                lane.qtab = ecdsa.qtable_slot_set(
                    lane.qtab, np.int32(slot), np_tab)
                self.stats["qtable_misses"] += 1
                incr_counter("verify", "qtable_misses")
            slots[j] = slot
            pinned.add(int(slot))
        set_gauge("verify", "qtable_occupancy",
                  value=float(lane.occupancy()))
        return slots, len(batch)

    def _dispatch(self, lane: _CurveLane, take: int) -> None:
        batch, lane.buf = lane.buf[:take], lane.buf[take:]
        while batch:
            batch = self._dispatch_some(lane, batch)

    def _dispatch_some(self, lane: _CurveLane,
                       batch: list[tuple]) -> list[tuple]:
        """Dispatch as many of ``batch``'s lanes as the Q-table can
        serve in one kernel execution; returns the unserved tail
        (non-empty only when a single batch references more distinct
        log keys than ``verifyQTableSize`` slots)."""
        from ct_mapreduce_tpu.ops import ecdsa

        key_idx = None
        if lane.window > 0:
            self._ensure_tables(lane)
            slots, consumed = self._resolve_slots(lane, batch)
            batch, rest = batch[:consumed], batch[consumed:]
            key_idx = np.zeros((self.batch_width,), np.int32)
            key_idx[:consumed] = slots
        else:
            rest = []
        n = len(batch)
        w = self.batch_width  # ONE compiled width per verifier
        bl = lane.ops.byte_len
        arr = lambda k: np.stack([b[k] for b in batch])  # noqa: E731

        def pad(a):
            return np.pad(np.ascontiguousarray(a, np.uint8),
                          ((0, w - n), (0, 0)))

        valid = np.pad(np.ones((n,), bool), (0, w - n))
        with trace.span("device.verify", cat="device", lanes=n,
                        curve=lane.ops.name):
            if lane.window == 0:
                out = ecdsa.jacobian_jit(lane.ops)(
                    pad(arr(0)), pad(arr(1)), pad(arr(2)),
                    pad(arr(3)), pad(arr(4)), valid,
                )
            else:
                out = ecdsa.windowed_jit(lane.ops)(
                    pad(arr(0)), pad(arr(1)), pad(arr(2)),
                    pad(arr(3)), pad(arr(4)), valid, key_idx,
                    lane.gtab, lane.qtab,
                )
        self.stats["batches"] += 1
        self.stats["device_lanes"] += n
        incr_counter("verify", "batches")
        incr_counter("verify", "device_lanes", value=float(n))
        add_sample("verify", "batch_lanes", value=float(n))
        self._inflight.append(_PendingVerify(
            out, n, np.array([b[5] for b in batch], np.int64)))
        return rest

    def _drain_inflight(self, keep: int) -> None:
        while len(self._inflight) > keep:
            p = self._inflight.popleft()
            verdicts = np.asarray(p.out)[: p.n]  # the blocking read
            self._fold_verdicts(verdicts, p.issuer_idx)

    def _fold_verdicts(self, verdicts: np.ndarray,
                       issuer_idx: np.ndarray) -> None:
        if len(verdicts) == 0:
            return
        v = int(verdicts.sum())
        f = len(verdicts) - v
        self.stats["verified"] += v
        self.stats["failed"] += f
        if v:
            incr_counter("verify", "verified", value=float(v))
        if f:
            incr_counter("verify", "failed", value=float(f))
        agg = self.agg
        with agg._fold_lock:
            agg.grow_verify_totals(int(issuer_idx.max(initial=0)))
            np.add.at(agg.verify_verified, issuer_idx, verdicts)
            np.add.at(agg.verify_failed, issuer_idx, ~verdicts)

    def drain(self) -> None:
        """Flush the staging buffers (padding each tail to the
        compiled width) and fold every outstanding batch."""
        for lane in self._lanes.values():
            while lane.buf:
                self._dispatch(lane, min(len(lane.buf), self.batch_width))
        self._drain_inflight(0)

    def health(self) -> dict:
        """The /healthz ``verify`` section: knobs, outcome totals, and
        per-curve Q-table occupancy."""
        return {
            "window": self.window,
            "stats": dict(self.stats),
            "qtable": {
                name: {
                    "capacity": lane.capacity,
                    "occupancy": lane.occupancy(),
                }
                for name, lane in sorted(self._lanes.items())
            },
        }


def _table_key(log_id: bytes, key: dict) -> tuple:
    """Q-table cache identity: the registry entry + its registration
    epoch (re-registration invalidates just this key) + coordinates
    (what the table bytes actually depend on)."""
    return (log_id, key.get("_epoch", 0),
            int(key["x"], 16), int(key["y"], 16))


def _key_coord(key: dict, name: str, nbytes: int = 32) -> np.ndarray:
    c = key.get(f"_{name}_bytes_{nbytes}")
    if c is None:
        c = np.frombuffer(
            int(key[name], 16).to_bytes(nbytes, "big"), np.uint8)
        key[f"_{name}_bytes_{nbytes}"] = c  # parse hex once per key
    return c
