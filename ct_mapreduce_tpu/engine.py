"""Bootstrap wiring: choose backends and telemetry from configuration.

Reference: /root/reference/engine/engine.go — GetConfiguredStorage
(:19-48) picks local-disk iff certPath is set (else noop) and requires
the cache; PrepareTelemetry (:50-86) picks StatsD when configured, else
an in-memory sink with a periodic stderr dumper.

The TPU build generalizes the cache choice: `redisHost` selects the
Redis-parity fabric; otherwise an in-process MockRemoteCache serves
single-process runs (the on-device aggregate path needs no external
cache at all — see agg/aggregator.py).
"""

from __future__ import annotations

from typing import Optional

from ct_mapreduce_tpu.config import CTConfig
from ct_mapreduce_tpu.storage.certdb import FilesystemDatabase
from ct_mapreduce_tpu.storage.interfaces import RemoteCache, StorageBackend
from ct_mapreduce_tpu.storage.localdisk import LocalDiskBackend
from ct_mapreduce_tpu.storage.mockcache import MockRemoteCache
from ct_mapreduce_tpu.storage.noop import NoopBackend
from ct_mapreduce_tpu.telemetry import flight, metrics
from ct_mapreduce_tpu.telemetry.metrics import InMemSink, MetricsDumper, StatsdSink
from ct_mapreduce_tpu.utils import parse_duration


def get_configured_storage(
    config: CTConfig,
) -> tuple[FilesystemDatabase, RemoteCache, StorageBackend]:
    """engine.go:19-48 analog."""
    if config.redis_host:
        from ct_mapreduce_tpu.storage.rediscache import RedisCache

        cache: RemoteCache = RedisCache(
            config.redis_host, timeout_s=parse_duration(config.redis_timeout)
        )
    else:
        cache = MockRemoteCache()

    backend: StorageBackend
    if config.cert_path:
        backend = LocalDiskBackend(config.cert_path)
    else:
        backend = NoopBackend()

    database = FilesystemDatabase(backend, cache)
    return database, cache, backend


def prepare_telemetry(name: str, config: CTConfig) -> Optional[MetricsDumper]:
    """engine.go:50-86 analog; returns the dumper so callers can stop
    it on shutdown.

    Unlike the reference's either/or (StatsD XOR in-mem dumper), an
    ``InMemSink`` is ALWAYS the primary sink and StatsD — when
    configured — rides as a fanout emitter: ``MetricsDumper``, the
    Prometheus ``/metrics`` endpoint, and the flight recorder all need
    ``snapshot()``, which ``StatsdSink`` cannot provide. The dumper's
    periodic snapshots also feed the flight recorder's last-N ring
    (a no-op until ``flight.install`` runs)."""
    sink = InMemSink()
    if config.statsd_host and config.statsd_port:
        metrics.set_sink(
            sink,
            StatsdSink(config.statsd_host, config.statsd_port, f"{name}."),
        )
    else:
        metrics.set_sink(sink)
    dumper = MetricsDumper(
        sink,
        parse_duration(config.stats_refresh_period),
        on_snapshot=flight.record_snapshot,
    )
    dumper.start()
    return dumper
