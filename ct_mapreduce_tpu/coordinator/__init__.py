from ct_mapreduce_tpu.coordinator.coordinator import Coordinator  # noqa: F401
