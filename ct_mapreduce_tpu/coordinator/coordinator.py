"""Multi-process coordination: leader election and a start barrier over
the RemoteCache fabric.

Reference: /root/reference/coordinator/coordinator.go — SETNX-based
election on `leader-<name>` with a background lease-renewal thread
(:44-85), followers polling `started-<leaderID>` (:87-106), the leader
publishing it (:108-138). Lease expiry gives elastic leader failover.

For TPU multi-host jobs the same contract is also available natively:
ct_mapreduce_tpu.parallel.distributed maps leadership to
jax.distributed process_index 0 with the barrier as a collective over
DCN — this Redis-parity coordinator remains for drop-in use alongside
reference deployments.
"""

from __future__ import annotations

import random
import socket
import threading
import time
from datetime import timedelta

from ct_mapreduce_tpu.storage.interfaces import RemoteCache

LEADER_KEY_PREFIX = "leader-"
STARTED_KEY_PREFIX = "started-"


class Coordinator:
    def __init__(
        self,
        cache: RemoteCache,
        name: str,
        key_life_initial: timedelta = timedelta(minutes=5),
        key_life_renewal: timedelta = timedelta(minutes=2),
        renewal_period_s: float = 60.0,
        await_sleep_period_s: float = 0.25,
    ):
        self.cache = cache
        self.name = name
        self.is_leader = False
        self.identifier = ""
        self.key_life_initial = key_life_initial
        self.key_life_renewal = key_life_renewal
        self.renewal_period_s = renewal_period_s
        self.await_sleep_period_s = await_sleep_period_s
        self._stop_renewal = threading.Event()
        self._renewal_threads: list[threading.Thread] = []

    def _start_renewal(self, key: str) -> None:
        def renew():
            while not self._stop_renewal.wait(self.renewal_period_s):
                try:
                    self.cache.expire_in(key, self.key_life_renewal)
                except Exception:
                    pass  # transient cache failures must not kill renewal

        # First renewal immediately, as the reference does (coordinator.go:71-79)
        self.cache.expire_in(key, self.key_life_renewal)
        t = threading.Thread(target=renew, name=f"renew-{key}", daemon=True)
        t.start()
        self._renewal_threads.append(t)

    def await_leader(self) -> bool:
        """Contend for leadership; returns True iff this process won
        (coordinator.go:44-85)."""
        our_identifier = f"{socket.gethostname()}-{random.getrandbits(63):X}"
        leader_key = LEADER_KEY_PREFIX + self.name
        result = self.cache.try_set(leader_key, our_identifier, self.key_life_initial)
        self.identifier = result
        self.is_leader = result == our_identifier
        if self.is_leader:
            self._start_renewal(leader_key)
        return self.is_leader

    def await_start(self, timeout_s: float | None = None) -> None:
        """Follower: poll until the leader publishes start
        (coordinator.go:87-106)."""
        if not self.identifier:
            raise RuntimeError("Must not call before await_leader completes")
        if self.is_leader:
            raise RuntimeError("Must not call unless we're a follower")
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        while True:
            if self.cache.exists(STARTED_KEY_PREFIX + self.identifier):
                return
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError("start barrier")
            time.sleep(self.await_sleep_period_s)

    def send_start(self) -> None:
        """Leader: publish the start barrier (coordinator.go:108-138)."""
        if not self.identifier:
            raise RuntimeError("Must not call before await_leader completes")
        if not self.is_leader:
            raise RuntimeError("Must not call unless we're leader")
        started_key = STARTED_KEY_PREFIX + self.identifier
        result = self.cache.try_set(
            started_key, self.identifier, self.key_life_initial
        )
        if result != self.identifier:
            raise RuntimeError(
                f"TrySet should have succeeded, put {self.identifier} got {result}"
            )
        self._start_renewal(started_key)

    def close(self) -> None:
        self._stop_renewal.set()
